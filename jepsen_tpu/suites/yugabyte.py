"""YugabyteDB suite: the dual-API (ycql/ysql) workload × nemesis matrix.

The reference's yugabyte suite (yugabyte/, 3567 LoC) is the most modern
in the monorepo: NAMESPACED workloads — every test exists for both the
Cassandra-dialect YCQL API and the PostgreSQL-dialect YSQL API — swept
against combined nemeses (yugabyte/src/yugabyte/core.clj:73-103's
workloads-ycql/workloads-ysql maps, `test-all` combinatorics :181-201).
This suite mirrors that structure on this framework:

- ycql workloads (over ``ycqlsh``): counter, set, set-index, bank,
  long-fork, single-key-acid, multi-key-acid;
- ysql workloads (over ``ysqlsh``): counter, set, bank,
  bank-multitable, long-fork, single-key-acid, multi-key-acid, append,
  append-table, default-value;
- faults: any subset of partition/kill/pause/clock through the combined
  nemesis-package algebra (nemesis/combined.py), exactly as the
  reference composes master/tserver killers with partitions and skews;
- `test-all` sweeps the workload × fault-set matrix from one CLI.

Workload names are namespaced like the reference's ("ycql/bank",
"ysql/append"); bare legacy names resolve to the ysql variants. The DB
runs master + tserver daemons per node (yugabyte/src/yugabyte/db.clj
topology).
"""

from __future__ import annotations

import json
import zlib
from typing import Any, Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from .. import independent
from ..models import CasRegister, MultiRegister
from ..nemesis import combined as ncombined
from .. import net as jnet
from ..checker import checker_fn
from ..control import util as cu
from ..workloads import append as wa
from ..workloads import bank as wbank
from ..workloads import linearizable_register as wreg
from ..workloads import long_fork as wlf
from .. import control as c
from . import std_generator

YSQLSH = "/opt/yugabyte/bin/ysqlsh"
YCQLSH = "/opt/yugabyte/bin/ycqlsh"
BANK_TABLE = "jepsen_bank"
APPEND_TABLE = "jepsen_append"
SET_TABLE = "jepsen_set"
KV_TABLE = "jepsen_kv"
COUNTER_TABLE = "jepsen_counter"
MULTI_TABLE = "jepsen_multi"
DV_TABLE = "jepsen_dv"
NULL_SENTINEL = "JEPSEN_NULL"
KEYSPACE = "jepsen"


class _YsqlClient(jclient.Client):
    """SQL over ysqlsh on the node (yugabyte's JDBC analogue)."""

    def __init__(self, node: Any = None):
        self.node = node

    def open(self, test, node):
        return type(self)(node)

    def _sql(self, test, script: str) -> str:
        def run(t, node):
            return c.exec_star(
                f"{YSQLSH} -h 127.0.0.1 -U yugabyte -At "
                f"-v ON_ERROR_STOP=1 <<'JEPSEN_SQL'\n"
                f"{script}\nJEPSEN_SQL")

        return c.on_nodes(test, run, [self.node])[self.node]

    @staticmethod
    def _definite_fail(e: Exception) -> bool:
        s = str(e).lower()
        return ("could not serialize" in s or "conflict" in s
                or "restart read" in s or "deadlock" in s
                or "constraint" in s)


class BankClient(_YsqlClient):
    def setup(self, test):
        rows = ", ".join(
            f"({a}, {b})" for a, b in wbank.initial_balances(test))
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {BANK_TABLE} "
                  "(id INT PRIMARY KEY, balance BIGINT NOT NULL CHECK (balance >= 0));\n"
                  f"INSERT INTO {BANK_TABLE} VALUES {rows} "
                  "ON CONFLICT (id) DO NOTHING;")

    def invoke(self, test, op):
        if op["f"] == "read":
            out = self._sql(test,
                            f"SELECT id, balance FROM {BANK_TABLE};")
            lines = [l.split("|") for l in out.strip().split("\n")
                     if l.strip()]
            value = {int(i): int(b) for i, b in lines}
            return {**op, "type": "ok", "value": value}
        v = op["value"]
        try:
            self._sql(test, "\n".join([
                "BEGIN ISOLATION LEVEL SERIALIZABLE;",
                f"UPDATE {BANK_TABLE} SET balance = balance - {v['amount']} "
                f"WHERE id = {v['from']};",
                f"UPDATE {BANK_TABLE} SET balance = balance + {v['amount']} "
                f"WHERE id = {v['to']};",
                "COMMIT;",
            ]))
            return {**op, "type": "ok"}
        except c.RemoteError as e:
            if self._definite_fail(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise


class AppendClient(_YsqlClient):
    """ysql/append.clj: list-append over JSONB in serializable txns."""

    def setup(self, test):
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {APPEND_TABLE} "
                  "(k TEXT PRIMARY KEY, v JSONB NOT NULL);")

    def invoke(self, test, op):
        stmts = ["BEGIN ISOLATION LEVEL SERIALIZABLE;"]
        for f, k, v in op["value"]:
            if f == "r":
                stmts.append(
                    f"SELECT COALESCE((SELECT v FROM {APPEND_TABLE} "
                    f"WHERE k = '{k}'), '[]'::jsonb);")
            else:
                stmts.append(
                    f"INSERT INTO {APPEND_TABLE} VALUES ('{k}', "
                    f"'[{v}]'::jsonb) ON CONFLICT (k) DO UPDATE SET "
                    f"v = {APPEND_TABLE}.v || '{v}'::jsonb;")
        stmts.append("COMMIT;")
        try:
            out = self._sql(test, "\n".join(stmts))
        except c.RemoteError as e:
            if self._definite_fail(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise
        lines = [l for l in out.strip().split("\n")
                 if l.strip().startswith("[")]
        done = []
        ri = 0
        for f, k, v in op["value"]:
            if f == "r":
                done.append([f, k, json.loads(lines[ri])])
                ri += 1
            else:
                done.append([f, k, v])
        return {**op, "type": "ok", "value": done}


class SetClient(_YsqlClient):
    def setup(self, test):
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {SET_TABLE} "
                  "(v BIGINT PRIMARY KEY);")

    def invoke(self, test, op):
        if op["f"] == "add":
            self._sql(test, f"INSERT INTO {SET_TABLE} VALUES "
                            f"({op['value']});")
            return {**op, "type": "ok"}
        if op["f"] == "read":
            try:
                out = self._sql(test, f"SELECT v FROM {SET_TABLE};")
            except c.RemoteError:
                return {**op, "type": "fail", "error": "sql"}
            vals = sorted(int(l) for l in out.strip().split("\n")
                          if l.strip())
            return {**op, "type": "ok", "value": vals}
        raise ValueError(f"unknown f {op['f']!r}")


def _psql_lines(out: str) -> list[str]:
    return [line for line in out.strip().split("\n") if line.strip()]


class YsqlCounterClient(_YsqlClient):
    """Single-row counter increments (ysql/counter.clj)."""

    def setup(self, test):
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {COUNTER_TABLE} "
                  "(id INT PRIMARY KEY, count BIGINT);\n"
                  f"INSERT INTO {COUNTER_TABLE} VALUES (0, 0) "
                  "ON CONFLICT (id) DO NOTHING;")

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                out = self._sql(
                    test, f"SELECT count FROM {COUNTER_TABLE} WHERE id = 0;")
                return {**op, "type": "ok",
                        "value": int(_psql_lines(out)[0])}
            self._sql(test,
                      f"UPDATE {COUNTER_TABLE} SET count = count + "
                      f"{op['value']} WHERE id = 0;")
            return {**op, "type": "ok"}
        except c.RemoteError as e:
            if self._definite_fail(e) or op["f"] == "read":
                return {**op, "type": "fail", "error": "sql"}
            raise


class YsqlKvTxnClient(_YsqlClient):
    """Generic micro-op txn client over (id, val) — one serializable
    script per txn, reads COALESCE-sentineled (long-fork's client
    shape, ysql/long_fork.clj)."""

    def setup(self, test):
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {KV_TABLE} "
                  "(id INT PRIMARY KEY, val INT);")

    def invoke(self, test, op):
        mops = op["value"]
        stmts = ["BEGIN ISOLATION LEVEL SERIALIZABLE;"]
        for f, k, v in mops:
            if f == "r":
                stmts.append(
                    f"SELECT COALESCE((SELECT val::TEXT FROM {KV_TABLE} "
                    f"WHERE id = {k}), '{NULL_SENTINEL}');")
            else:
                stmts.append(
                    f"INSERT INTO {KV_TABLE} VALUES ({k}, {v}) "
                    f"ON CONFLICT (id) DO UPDATE SET val = {v};")
        stmts.append("COMMIT;")
        try:
            out = self._sql(test, "\n".join(stmts))
        except c.RemoteError as e:
            if self._definite_fail(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise
        lines = _psql_lines(out)
        done = []
        ri = 0
        for f, k, v in mops:
            if f == "r":
                line = lines[ri]
                ri += 1
                done.append(
                    ["r", k, None if line == NULL_SENTINEL else int(line)])
            else:
                done.append([f, k, v])
        return {**op, "type": "ok", "value": done}


class YsqlSingleKeyClient(_YsqlClient):
    """Keyed linearizable register (ysql/single_key_acid.clj): cas via
    a guarded UPDATE … RETURNING."""

    def setup(self, test):
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {KV_TABLE}_acid "
                  "(id INT PRIMARY KEY, val INT);")

    def invoke(self, test, op):
        k, v = op["value"]
        t = f"{KV_TABLE}_acid"
        try:
            if op["f"] == "read":
                out = self._sql(
                    test,
                    f"SELECT COALESCE((SELECT val::TEXT FROM {t} "
                    f"WHERE id = {k}), '{NULL_SENTINEL}');")
                line = _psql_lines(out)[0]
                val = None if line == NULL_SENTINEL else int(line)
                return {**op, "type": "ok",
                        "value": independent.tuple_(k, val)}
            if op["f"] == "write":
                self._sql(test,
                          f"INSERT INTO {t} VALUES ({k}, {v}) "
                          f"ON CONFLICT (id) DO UPDATE SET val = {v};")
                return {**op, "type": "ok"}
            old, new = v
            out = self._sql(test,
                            f"UPDATE {t} SET val = {new} "
                            f"WHERE id = {k} AND val = {old} RETURNING id;")
            hit = any(line.strip() == str(k) for line in _psql_lines(out))
            return {**op, "type": "ok" if hit else "fail",
                    **({} if hit else {"error": "precondition-failed"})}
        except c.RemoteError as e:
            if self._definite_fail(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise


class YsqlMultiKeyClient(_YsqlClient):
    """Transactional multi-register batches (ysql/multi_key_acid.clj):
    keyed rows (ik, k) written in one serializable txn; ops carry
    {reg: value} maps for the multi-register model."""

    def setup(self, test):
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {MULTI_TABLE} "
                  "(ik INT, k INT, val INT, PRIMARY KEY (ik, k));")

    def invoke(self, test, op):
        ik, regs = op["value"]
        try:
            if op["f"] == "read":
                ks = sorted(regs)
                stmts = ["BEGIN ISOLATION LEVEL SERIALIZABLE;"] + [
                    f"SELECT COALESCE((SELECT val::TEXT FROM {MULTI_TABLE}"
                    f" WHERE ik = {ik} AND k = {k}), '{NULL_SENTINEL}');"
                    for k in ks
                ] + ["COMMIT;"]
                out = self._sql(test, "\n".join(stmts))
                vals = [None if line == NULL_SENTINEL else int(line)
                        for line in _psql_lines(out)]
                return {**op, "type": "ok", "value": independent.tuple_(
                    ik, dict(zip(ks, vals)))}
            stmts = ["BEGIN ISOLATION LEVEL SERIALIZABLE;"] + [
                f"INSERT INTO {MULTI_TABLE} VALUES ({ik}, {k}, {v}) "
                f"ON CONFLICT (ik, k) DO UPDATE SET val = {v};"
                for k, v in sorted(regs.items())
            ] + ["COMMIT;"]
            self._sql(test, "\n".join(stmts))
            return {**op, "type": "ok"}
        except c.RemoteError as e:
            if self._definite_fail(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise


class BankMultitableClient(_YsqlClient):
    """Bank with one table per account (ysql/bank.clj's
    YSQLMultiBankClient): transfers touch two tables in one txn."""

    @staticmethod
    def _table(acct) -> str:
        return f"{BANK_TABLE}_{acct}"

    def setup(self, test):
        stmts = []
        for a, b in wbank.initial_balances(test):
            stmts.append(
                f"CREATE TABLE IF NOT EXISTS {self._table(a)} "
                "(id INT PRIMARY KEY, balance BIGINT NOT NULL);")
            stmts.append(
                f"INSERT INTO {self._table(a)} VALUES ({a}, {b}) "
                "ON CONFLICT (id) DO NOTHING;")
        self._sql(test, "\n".join(stmts))

    def invoke(self, test, op):
        accounts = list(test.get("accounts") or [])
        try:
            if op["f"] == "read":
                stmts = ["BEGIN ISOLATION LEVEL SERIALIZABLE;"] + [
                    f"SELECT id, balance FROM {self._table(a)};"
                    for a in accounts
                ] + ["COMMIT;"]
                out = self._sql(test, "\n".join(stmts))
                value = {}
                for line in _psql_lines(out):
                    if "|" in line:
                        i, b = line.split("|")[:2]
                        value[int(i)] = int(b)
                return {**op, "type": "ok", "value": value}
            v = op["value"]
            self._sql(test, "\n".join([
                "BEGIN ISOLATION LEVEL SERIALIZABLE;",
                f"UPDATE {self._table(v['from'])} SET balance = balance - "
                f"{v['amount']} WHERE id = {v['from']};",
                f"UPDATE {self._table(v['to'])} SET balance = balance + "
                f"{v['amount']} WHERE id = {v['to']};",
                "COMMIT;",
            ]))
            return {**op, "type": "ok"}
        except c.RemoteError as e:
            if self._definite_fail(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise


class AppendTableClient(_YsqlClient):
    """List-append with table-sharded lists (ysql/append_table.clj):
    appends are ordered rows, reads select them back in insertion
    order. The generator's key space is unbounded, so keys hash into a
    fixed table pool, each row carrying its key (two keys sharing a
    table can't contaminate each other's lists)."""

    TABLES = 8

    @classmethod
    def _table(cls, k) -> str:
        return f"{APPEND_TABLE}_k{zlib.crc32(str(k).encode()) % cls.TABLES}"

    def setup(self, test):
        stmts = [
            f"CREATE TABLE IF NOT EXISTS {APPEND_TABLE}_k{i} "
            "(id BIGSERIAL PRIMARY KEY, k INT, v INT);"
            for i in range(self.TABLES)
        ]
        self._sql(test, "\n".join(stmts))

    def invoke(self, test, op):
        stmts = ["BEGIN ISOLATION LEVEL SERIALIZABLE;"]
        for f, k, v in op["value"]:
            if f == "r":
                stmts.append(
                    f"SELECT COALESCE((SELECT json_agg(v ORDER BY id)::TEXT "
                    f"FROM {self._table(k)} WHERE k = {k}), '[]');")
            else:
                stmts.append(
                    f"INSERT INTO {self._table(k)} (k, v) "
                    f"VALUES ({k}, {v});")
        stmts.append("COMMIT;")
        try:
            out = self._sql(test, "\n".join(stmts))
        except c.RemoteError as e:
            if self._definite_fail(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise
        lines = [line for line in _psql_lines(out)
                 if line.strip().startswith("[")]
        done = []
        ri = 0
        for f, k, v in op["value"]:
            if f == "r":
                done.append([f, k, json.loads(lines[ri])])
                ri += 1
            else:
                done.append([f, k, v])
        return {**op, "type": "ok", "value": done}


class DefaultValueClient(_YsqlClient):
    """Concurrent DDL vs DML (default_value.clj): create/drop the table
    while inserting and reading; reads must never observe null column
    values."""

    def invoke(self, test, op):
        f = op["f"]
        try:
            if f == "create-table":
                self._sql(test,
                          f"CREATE TABLE IF NOT EXISTS {DV_TABLE} "
                          "(id BIGSERIAL PRIMARY KEY, v INT NOT NULL "
                          "DEFAULT 0);")
                return {**op, "type": "ok"}
            if f == "drop-table":
                self._sql(test, f"DROP TABLE IF EXISTS {DV_TABLE};")
                return {**op, "type": "ok"}
            if f == "insert":
                self._sql(test,
                          f"INSERT INTO {DV_TABLE} (v) VALUES (0);")
                return {**op, "type": "ok"}
            out = self._sql(
                test,
                f"SELECT id, COALESCE(v::TEXT, '{NULL_SENTINEL}') "
                f"FROM {DV_TABLE};")
            rows = []
            for line in _psql_lines(out):
                if "|" in line:
                    i, v = line.split("|")[:2]
                    rows.append({"id": int(i),
                                 "v": None if v.strip() == NULL_SENTINEL
                                 else int(v)})
            return {**op, "type": "ok", "value": rows}
        except c.RemoteError as e:
            # DDL races produce transient "does not exist" errors —
            # definite fails for every op class here.
            return {**op, "type": "fail", "error": "sql"}


# --- YCQL (Cassandra dialect over ycqlsh) ----------------------------------


class _YcqlClient(jclient.Client):
    """CQL over ycqlsh on the node (the cassaforte-driver analogue,
    ycql/client.clj)."""

    def __init__(self, node: Any = None):
        self.node = node

    def open(self, test, node):
        return type(self)(node)

    def _cql(self, test, script: str) -> str:
        def run(t, node):
            return c.exec_star(
                f"{YCQLSH} 127.0.0.1 9042 <<'JEPSEN_CQL'\n"
                f"{script}\nJEPSEN_CQL")

        return c.on_nodes(test, run, [self.node])[self.node]

    def setup_keyspace(self, test):
        self._cql(test,
                  f"CREATE KEYSPACE IF NOT EXISTS {KEYSPACE} "
                  "WITH replication = {'class': 'SimpleStrategy'};")

    @staticmethod
    def _definite_fail(e: Exception) -> bool:
        s = str(e).lower()
        return ("conflict" in s or "aborted" in s or "expired" in s
                or "condition" in s)

    @staticmethod
    def _rows(out: str) -> list[list[str]]:
        """ycqlsh prints ` a | b ` rows plus headers/rules/"(n rows)";
        data rows are those whose cells are all numeric (or null) —
        single-column results have no ``|`` separator at all."""
        rows = []
        for line in out.strip().split("\n"):
            stripped = line.strip()
            if not stripped or "rows)" in stripped \
                    or set(stripped) <= {"-", "+"}:
                continue
            cells = ([x.strip() for x in line.split("|")]
                     if "|" in line else [stripped])
            vals = [x for x in cells if x != ""]
            if vals and all(x == "null" or x.lstrip("-").isdigit()
                            for x in vals):
                rows.append(cells)
        return rows


class CqlCounterClient(_YcqlClient):
    """Distributed counter column (ycql/counter.clj)."""

    def setup(self, test):
        self.setup_keyspace(test)
        self._cql(test,
                  f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.counter "
                  "(id INT PRIMARY KEY, count COUNTER);\n"
                  f"UPDATE {KEYSPACE}.counter SET count = count + 0 "
                  "WHERE id = 0;")

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                out = self._cql(
                    test, f"SELECT count FROM {KEYSPACE}.counter "
                          "WHERE id = 0;")
                rows = self._rows(out)
                val = int(rows[0][0]) if rows else 0
                return {**op, "type": "ok", "value": val}
            self._cql(test,
                      f"UPDATE {KEYSPACE}.counter SET count = count + "
                      f"{op['value']} WHERE id = 0;")
            return {**op, "type": "ok"}
        except c.RemoteError as e:
            if self._definite_fail(e) or op["f"] == "read":
                return {**op, "type": "fail", "error": "cql"}
            raise


class CqlSetClient(_YcqlClient):
    """Unique inserts + full reads (ycql/set.clj); ``use_index`` reads
    through a secondary index the way CQLSetIndexClient does."""

    def __init__(self, node: Any = None, use_index: bool = False):
        super().__init__(node)
        self.use_index = use_index

    def open(self, test, node):
        return type(self)(node, self.use_index)

    def setup(self, test):
        self.setup_keyspace(test)
        stmts = [f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.elements "
                 "(val BIGINT PRIMARY KEY, present BOOLEAN) "
                 "WITH transactions = {'enabled': true};"]
        if self.use_index:
            stmts.append(
                f"CREATE INDEX IF NOT EXISTS elements_present "
                f"ON {KEYSPACE}.elements (present);")
        self._cql(test, "\n".join(stmts))

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                self._cql(test,
                          f"INSERT INTO {KEYSPACE}.elements "
                          f"(val, present) VALUES ({op['value']}, true);")
                return {**op, "type": "ok"}
            where = " WHERE present = true" if self.use_index else ""
            out = self._cql(
                test, f"SELECT val FROM {KEYSPACE}.elements{where};")
            vals = sorted(int(r[0]) for r in self._rows(out))
            return {**op, "type": "ok", "value": vals}
        except c.RemoteError as e:
            if self._definite_fail(e) or op["f"] == "read":
                return {**op, "type": "fail", "error": "cql"}
            raise


class CqlBankClient(_YcqlClient):
    """Transfers in one YCQL transaction block (ycql/bank.clj) —
    negative balances allowed (workload-allow-neg, core.clj:84)."""

    def setup(self, test):
        self.setup_keyspace(test)
        rows = "\n".join(
            f"INSERT INTO {KEYSPACE}.bank (id, balance) "
            f"VALUES ({a}, {b}) IF NOT EXISTS;"
            for a, b in wbank.initial_balances(test))
        self._cql(test,
                  f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.bank "
                  "(id INT PRIMARY KEY, balance BIGINT) "
                  "WITH transactions = {'enabled': true};\n" + rows)

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                out = self._cql(
                    test, f"SELECT id, balance FROM {KEYSPACE}.bank;")
                value = {int(r[0]): int(r[1]) for r in self._rows(out)}
                return {**op, "type": "ok", "value": value}
            v = op["value"]
            self._cql(test, "\n".join([
                "BEGIN TRANSACTION",
                f"UPDATE {KEYSPACE}.bank SET balance = balance - "
                f"{v['amount']} WHERE id = {v['from']};",
                f"UPDATE {KEYSPACE}.bank SET balance = balance + "
                f"{v['amount']} WHERE id = {v['to']};",
                "END TRANSACTION;",
            ]))
            return {**op, "type": "ok"}
        except c.RemoteError as e:
            if self._definite_fail(e) or op["f"] == "read":
                return {**op, "type": "fail", "error": "cql"}
            raise


class CqlLongForkClient(_YcqlClient):
    """kv writes + IN-predicate multi-key reads
    (ycql/long_fork.clj)."""

    def setup(self, test):
        self.setup_keyspace(test)
        self._cql(test,
                  f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.kv "
                  "(id INT PRIMARY KEY, val INT) "
                  "WITH transactions = {'enabled': true};")

    def invoke(self, test, op):
        mops = op["value"]
        try:
            writes = [(k, v) for f, k, v in mops if f == "w"]
            if writes:
                stmts = [f"INSERT INTO {KEYSPACE}.kv (id, val) "
                         f"VALUES ({k}, {v});" for k, v in writes]
                self._cql(test, "\n".join(stmts))
                return {**op, "type": "ok", "value": mops}
            ks = [k for f, k, _v in mops]
            out = self._cql(
                test,
                f"SELECT id, val FROM {KEYSPACE}.kv WHERE id IN "
                f"({', '.join(str(k) for k in ks)});")
            got = {int(r[0]): int(r[1]) for r in self._rows(out)}
            done = [["r", k, got.get(k)] for k in ks]
            return {**op, "type": "ok", "value": done}
        except c.RemoteError as e:
            if self._definite_fail(e):
                return {**op, "type": "fail", "error": "cql"}
            raise


class CqlSingleKeyClient(_YcqlClient):
    """Keyed register with LWT cas (ycql/single_key_acid.clj): UPDATE
    … IF val = old, decided by the [applied] row."""

    def setup(self, test):
        self.setup_keyspace(test)
        self._cql(test,
                  f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.acid "
                  "(id INT PRIMARY KEY, val INT);")

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "read":
                out = self._cql(
                    test,
                    f"SELECT val FROM {KEYSPACE}.acid WHERE id = {k};")
                rows = self._rows(out)
                val = int(rows[0][0]) if rows else None
                return {**op, "type": "ok",
                        "value": independent.tuple_(k, val)}
            if op["f"] == "write":
                self._cql(test,
                          f"INSERT INTO {KEYSPACE}.acid (id, val) "
                          f"VALUES ({k}, {v});")
                return {**op, "type": "ok"}
            old, new = v
            out = self._cql(test,
                            f"UPDATE {KEYSPACE}.acid SET val = {new} "
                            f"WHERE id = {k} IF val = {old};")
            applied = "true" in out.lower()
            return {**op, "type": "ok" if applied else "fail",
                    **({} if applied else {"error": "precondition-failed"})}
        except c.RemoteError as e:
            if self._definite_fail(e) or op["f"] == "read":
                return {**op, "type": "fail",
                        "error": "precondition-failed"
                        if op["f"] == "cas" else "cql"}
            raise


class CqlMultiKeyClient(_YcqlClient):
    """Transactional multi-register batches over (ik, k)
    (ycql/multi_key_acid.clj)."""

    def setup(self, test):
        self.setup_keyspace(test)
        self._cql(test,
                  f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.multi "
                  "(ik INT, k INT, val INT, PRIMARY KEY ((ik), k)) "
                  "WITH transactions = {'enabled': true};")

    def invoke(self, test, op):
        ik, regs = op["value"]
        try:
            if op["f"] == "read":
                ks = sorted(regs)
                out = self._cql(
                    test,
                    f"SELECT k, val FROM {KEYSPACE}.multi "
                    f"WHERE ik = {ik};")
                got = {int(r[0]): int(r[1]) for r in self._rows(out)}
                return {**op, "type": "ok", "value": independent.tuple_(
                    ik, {k: got.get(k) for k in ks})}
            stmts = ["BEGIN TRANSACTION"] + [
                f"INSERT INTO {KEYSPACE}.multi (ik, k, val) "
                f"VALUES ({ik}, {k}, {v});"
                for k, v in sorted(regs.items())
            ] + ["END TRANSACTION;"]
            self._cql(test, "\n".join(stmts))
            return {**op, "type": "ok"}
        except c.RemoteError as e:
            if self._definite_fail(e) or op["f"] == "read":
                return {**op, "type": "fail", "error": "cql"}
            raise


class YugabyteDB(jdb.DB, jdb.Process, jdb.Pause, jdb.LogFiles):
    """master + tserver daemons per node (yugabyte/db.clj)."""

    URL = ("https://downloads.yugabyte.com/releases/2.20.1.3/"
           "yugabyte-2.20.1.3-b3-linux-x86_64.tar.gz")
    DIR = "/opt/yugabyte"
    LOGS = ["/var/log/yb-master.log", "/var/log/yb-tserver.log"]

    def setup(self, test, node):
        cu.install_archive(self.URL, self.DIR)
        with c.su():
            c.exec_star(f"{self.DIR}/bin/post_install.sh || true")
        self.start(test, node)

    def start(self, test, node):
        masters = ",".join(f"{n}:7100" for n in test["nodes"])
        with c.su():
            cu.start_daemon(
                {"logfile": self.LOGS[0], "pidfile": "/var/run/yb-master.pid",
                 "chdir": self.DIR},
                f"{self.DIR}/bin/yb-master",
                "--master_addresses", masters,
                "--rpc_bind_addresses", f"{node}:7100",
                "--fs_data_dirs", "/var/lib/yb-master",
            )
            cu.start_daemon(
                {"logfile": self.LOGS[1],
                 "pidfile": "/var/run/yb-tserver.pid", "chdir": self.DIR},
                f"{self.DIR}/bin/yb-tserver",
                "--tserver_master_addrs", masters,
                "--rpc_bind_addresses", f"{node}:9100",
                "--fs_data_dirs", "/var/lib/yb-tserver",
                "--start_pgsql_proxy",
                "--pgsql_proxy_bind_address", "0.0.0.0:5433",
            )

    def kill(self, test, node):
        cu.grepkill("yb-tserver")
        cu.grepkill("yb-master")

    def pause(self, test, node):
        cu.grepkill("yb-tserver", signal="STOP")

    def resume(self, test, node):
        cu.grepkill("yb-tserver", signal="CONT")

    def teardown(self, test, node):
        self.kill(test, node)
        with c.su():
            c.exec("rm", "-rf", "/var/lib/yb-master", "/var/lib/yb-tserver")

    def log_files(self, test, node):
        return list(self.LOGS)


def bank_workload(opts: dict) -> dict:
    wl = wbank.test(opts)
    return {**wl, "client": BankClient()}


def append_workload(opts: dict) -> dict:
    wl = wa.test({"key_count": 4})
    return {"client": AppendClient(), "generator": wl["generator"],
            "checker": wl["checker"]}


def set_workload(opts: dict) -> dict:
    counter = [0]

    def add(test=None, ctx=None):
        counter[0] += 1
        return {"type": "invoke", "f": "add", "value": counter[0]}

    return {
        "client": SetClient(),
        "checker": jchecker.compose({
            "set": jchecker.set_checker(),
            "stats": jchecker.stats(),
        }),
        "generator": gen.clients(
            gen.limit(int(opts.get("ops") or 200), add)),
        "final-generator": gen.clients(
            gen.once({"type": "invoke", "f": "read", "value": None})),
    }


def _with_client(wl_fn, client_cls, **ckw):
    """core.clj:71-75's with-client: same workload, different API
    client."""

    def fn(opts):
        wl = wl_fn(opts)
        return {**wl, "client": client_cls(**ckw)}

    return fn


def _bank_allow_neg(client_cls):
    """bank/workload-allow-neg (core.clj:84,95): negative balances are
    legal — reproducing errors is easier without the CHECK."""

    def fn(opts):
        wl = wbank.test({**opts, "negative-balances?": True})
        return {**wl, "client": client_cls()}

    return fn


def counter_workload(client_cls):
    """counter.clj:9-22: mostly increments, occasional reads."""

    def fn(opts):
        def add(t=None, ctx=None):
            return {"type": "invoke", "f": "add", "value": 1}

        def read(t=None, ctx=None):
            return {"type": "invoke", "f": "read", "value": None}

        return {
            "client": client_cls(),
            "generator": gen.delay(0.1, gen.mix([read, add, add, add])),
            "checker": jchecker.compose({
                "counter": jchecker.counter(),
                "stats": jchecker.stats(),
            }),
        }

    return fn


def single_key_acid_workload(client_cls):
    """single_key_acid.clj:30-46: keyed linearizable cas register."""

    def fn(opts):
        wl = wreg.test({**(opts or {}), "model": CasRegister(init=None)})
        return {**wl, "client": client_cls(),
                "generator": gen.stagger(0.01, wl["generator"])}

    return fn


def _rand_nonempty_subset(pool):
    out = [k for k in pool if gen.rand_int(2)]
    return out or [pool[gen.rand_int(len(pool))]]


def multi_key_acid_workload(client_cls):
    """multi_key_acid.clj:40-72: keyed transactional multi-register
    batches, checked against the multi-register model."""

    KEY_RANGE = (0, 1, 2)

    def fn(opts):
        import itertools

        def read(t=None, ctx=None):
            ks = _rand_nonempty_subset(KEY_RANGE)
            return {"type": "invoke", "f": "read",
                    "value": {k: None for k in ks}}

        def write(t=None, ctx=None):
            ks = _rand_nonempty_subset(KEY_RANGE)
            return {"type": "invoke", "f": "write",
                    "value": {k: gen.rand_int(5) for k in ks}}

        def fgen(k):
            return gen.process_limit(
                20, gen.stagger(0.05, gen.reserve(2, read, write)))

        return {
            "client": client_cls(),
            "generator": independent.concurrent_generator(
                4, itertools.count(), fgen),
            "checker": independent.checker(jchecker.compose({
                "linear": jchecker.linearizable(
                    model=MultiRegister(init={k: None for k in KEY_RANGE})),
                "stats": jchecker.stats(),
            })),
        }

    return fn


def long_fork_workload(client_cls):
    def fn(opts):
        wl = wlf.workload(3)
        return {**wl, "client": client_cls()}

    return fn


def dv_checker() -> jchecker.Checker:
    """No read may observe a row with a null column value
    (default_value.clj:28-61)."""

    def chk(test, history, opts):
        bad = []
        reads = 0
        for op in history:
            if not (op.is_ok and op.f == "read"):
                continue
            reads += 1
            rows = [r for r in (op.value or [])
                    if any(v is None for v in r.values())]
            if rows:
                bad.append({"op": repr(op), "bad-rows": rows})
        return {"valid": not bad, "read-count": reads,
                "bad-read-count": len(bad), "bad-reads": bad}

    return checker_fn(chk, "default-value")


def default_value_workload(opts):
    """default_value.clj:13-26: concurrent DDL (create/drop table) vs
    inserts and reads."""

    def mk(f):
        return lambda t=None, ctx=None: {
            "type": "invoke", "f": f, "value": None}

    return {
        "client": DefaultValueClient(),
        "generator": gen.stagger(0.01, gen.mix(
            [mk("create-table"), mk("drop-table")]
            + [mk("read"), mk("insert")] * 5)),
        "checker": jchecker.compose({
            "default-value": dv_checker(),
            "stats": jchecker.stats(),
        }),
    }


WORKLOADS = {
    # ycql (core.clj:76-88)
    "ycql/counter": counter_workload(CqlCounterClient),
    "ycql/set": _with_client(set_workload, CqlSetClient),
    "ycql/set-index": _with_client(set_workload, CqlSetClient,
                                   use_index=True),
    "ycql/bank": _bank_allow_neg(CqlBankClient),
    "ycql/long-fork": long_fork_workload(CqlLongForkClient),
    "ycql/single-key-acid": single_key_acid_workload(CqlSingleKeyClient),
    "ycql/multi-key-acid": multi_key_acid_workload(CqlMultiKeyClient),
    # ysql (core.clj:89-103)
    "ysql/counter": counter_workload(YsqlCounterClient),
    "ysql/set": set_workload,
    "ysql/bank": bank_workload,
    "ysql/bank-multitable": _bank_allow_neg(BankMultitableClient),
    "ysql/long-fork": long_fork_workload(YsqlKvTxnClient),
    "ysql/single-key-acid": single_key_acid_workload(YsqlSingleKeyClient),
    "ysql/multi-key-acid": multi_key_acid_workload(YsqlMultiKeyClient),
    "ysql/append": append_workload,
    "ysql/append-table": _with_client(append_workload, AppendTableClient),
    "ysql/default-value": default_value_workload,
}

# Bare names keep working (they pick the ysql variant).
ALIASES = {"bank": "ysql/bank", "append": "ysql/append",
           "set": "ysql/set"}


def test_fn(opts: dict) -> dict:
    """One cell of the workload × fault matrix (core.clj:73-161)."""
    name = opts.get("workload") or "ysql/append"
    name = ALIASES.get(name, name)
    wl = WORKLOADS[name](opts)
    db = YugabyteDB()
    raw_faults = opts.get("faults")
    if raw_faults is None:
        raw_faults = "partition,kill"
    faults = [f for f in raw_faults.split(",") if f]
    test = {
        # "/" nests store directories; names use the dashed form.
        "name": f"yugabyte-{name.replace('/', '-')}-"
                f"{'+'.join(faults) or 'none'}",
        "db": db,
        "net": jnet.iptables(),
    }
    if faults:
        pkg = ncombined.nemesis_package({
            "db": db,
            "interval": opts.get("nemesis_interval") or 10,
            "faults": faults,
        })
        test["nemesis"] = pkg["nemesis"]
        test["plot"] = {"nemeses": pkg["perf"]}
        phases = [
            gen.time_limit(
                opts.get("time_limit", 60),
                gen.nemesis(pkg["generator"], wl["generator"])),
            gen.nemesis(pkg["final-generator"]),
        ]
        if wl.get("final-generator") is not None:
            phases.append(wl["final-generator"])
        test["generator"] = gen.phases(*phases)
    else:
        test["generator"] = std_generator(
            opts, wl["generator"],
            final_client_gen=wl.get("final-generator"))
    test.update({k: v for k, v in wl.items()
                 if k not in ("generator", "final-generator")})
    return test


def matrix_test_fns(opts_base: dict | None = None) -> dict:
    """name -> test_fn closures for every workload × fault-set cell
    (yugabyte/core.clj:181-201 `test-all` combinatorics)."""
    fault_sets = ["partition", "kill", "partition,kill", ""]
    fns = {}
    for wname in WORKLOADS:
        for faults in fault_sets:
            label = (f"{wname.replace('/', '-')}-"
                     f"{faults.replace(',', '+') or 'none'}")

            def fn(opts, _w=wname, _f=faults):
                return test_fn({**opts, "workload": _w, "faults": _f})

            fns[label] = fn
    return fns


def _add_opts(p):
    p.add_argument("--workload",
                   choices=sorted(WORKLOADS) + sorted(ALIASES),
                   default="ysql/append")
    p.add_argument("--faults", default="partition,kill")
    p.add_argument("--nemesis-interval", type=int, default=10)
    p.add_argument("--ops", type=int, default=200)


def main(argv=None):
    cmds = dict(cli.single_test_cmd(test_fn, add_opts=_add_opts))
    cmds.update(cli.test_all_cmd(matrix_test_fns(),
                                 add_opts=_add_opts))
    cli.main_exit(cmds, argv)


if __name__ == "__main__":
    main()
