"""YugabyteDB suite: a workload × nemesis matrix over ysqlsh.

The reference's yugabyte suite (yugabyte/, 3567 LoC) is the most modern
in the monorepo: namespaced workloads swept against combined nemeses
(yugabyte/src/yugabyte/core.clj:73-161, `test-all` combinatorics
:181-201). This suite mirrors that structure on this framework:

- workloads: **append** (elle list-append over JSONB, the ysql/append
  shape), **bank**, **set** (unique inserts + final read);
- faults: any subset of partition/kill/pause/clock through the combined
  nemesis-package algebra (nemesis/combined.py), exactly as the
  reference composes master/tserver killers with partitions and skews;
- `test-all` sweeps the workload × fault-set matrix from one CLI.

Clients drive ``ysqlsh`` (YSQL is the PostgreSQL dialect) on the node;
the DB runs master + tserver daemons per node
(yugabyte/src/yugabyte/db.clj topology).
"""

from __future__ import annotations

import json
from typing import Any

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from ..nemesis import combined as ncombined
from .. import net as jnet
from ..control import util as cu
from ..workloads import append as wa
from ..workloads import bank as wbank
from .. import control as c
from . import std_generator

YSQLSH = "/opt/yugabyte/bin/ysqlsh"
BANK_TABLE = "jepsen_bank"
APPEND_TABLE = "jepsen_append"
SET_TABLE = "jepsen_set"


class _YsqlClient(jclient.Client):
    """SQL over ysqlsh on the node (yugabyte's JDBC analogue)."""

    def __init__(self, node: Any = None):
        self.node = node

    def open(self, test, node):
        return type(self)(node)

    def _sql(self, test, script: str) -> str:
        def run(t, node):
            return c.exec_star(
                f"{YSQLSH} -h 127.0.0.1 -U yugabyte -At "
                f"-v ON_ERROR_STOP=1 <<'JEPSEN_SQL'\n"
                f"{script}\nJEPSEN_SQL")

        return c.on_nodes(test, run, [self.node])[self.node]

    @staticmethod
    def _definite_fail(e: Exception) -> bool:
        s = str(e).lower()
        return ("could not serialize" in s or "conflict" in s
                or "restart read" in s or "deadlock" in s
                or "constraint" in s)


class BankClient(_YsqlClient):
    def setup(self, test):
        rows = ", ".join(
            f"({a}, {b})" for a, b in wbank.initial_balances(test))
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {BANK_TABLE} "
                  "(id INT PRIMARY KEY, balance BIGINT NOT NULL CHECK (balance >= 0));\n"
                  f"INSERT INTO {BANK_TABLE} VALUES {rows} "
                  "ON CONFLICT (id) DO NOTHING;")

    def invoke(self, test, op):
        if op["f"] == "read":
            out = self._sql(test,
                            f"SELECT id, balance FROM {BANK_TABLE};")
            lines = [l.split("|") for l in out.strip().split("\n")
                     if l.strip()]
            value = {int(i): int(b) for i, b in lines}
            return {**op, "type": "ok", "value": value}
        v = op["value"]
        try:
            self._sql(test, "\n".join([
                "BEGIN ISOLATION LEVEL SERIALIZABLE;",
                f"UPDATE {BANK_TABLE} SET balance = balance - {v['amount']} "
                f"WHERE id = {v['from']};",
                f"UPDATE {BANK_TABLE} SET balance = balance + {v['amount']} "
                f"WHERE id = {v['to']};",
                "COMMIT;",
            ]))
            return {**op, "type": "ok"}
        except c.RemoteError as e:
            if self._definite_fail(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise


class AppendClient(_YsqlClient):
    """ysql/append.clj: list-append over JSONB in serializable txns."""

    def setup(self, test):
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {APPEND_TABLE} "
                  "(k TEXT PRIMARY KEY, v JSONB NOT NULL);")

    def invoke(self, test, op):
        stmts = ["BEGIN ISOLATION LEVEL SERIALIZABLE;"]
        for f, k, v in op["value"]:
            if f == "r":
                stmts.append(
                    f"SELECT COALESCE((SELECT v FROM {APPEND_TABLE} "
                    f"WHERE k = '{k}'), '[]'::jsonb);")
            else:
                stmts.append(
                    f"INSERT INTO {APPEND_TABLE} VALUES ('{k}', "
                    f"'[{v}]'::jsonb) ON CONFLICT (k) DO UPDATE SET "
                    f"v = {APPEND_TABLE}.v || '{v}'::jsonb;")
        stmts.append("COMMIT;")
        try:
            out = self._sql(test, "\n".join(stmts))
        except c.RemoteError as e:
            if self._definite_fail(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise
        lines = [l for l in out.strip().split("\n")
                 if l.strip().startswith("[")]
        done = []
        ri = 0
        for f, k, v in op["value"]:
            if f == "r":
                done.append([f, k, json.loads(lines[ri])])
                ri += 1
            else:
                done.append([f, k, v])
        return {**op, "type": "ok", "value": done}


class SetClient(_YsqlClient):
    def setup(self, test):
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {SET_TABLE} "
                  "(v BIGINT PRIMARY KEY);")

    def invoke(self, test, op):
        if op["f"] == "add":
            self._sql(test, f"INSERT INTO {SET_TABLE} VALUES "
                            f"({op['value']});")
            return {**op, "type": "ok"}
        if op["f"] == "read":
            try:
                out = self._sql(test, f"SELECT v FROM {SET_TABLE};")
            except c.RemoteError:
                return {**op, "type": "fail", "error": "sql"}
            vals = sorted(int(l) for l in out.strip().split("\n")
                          if l.strip())
            return {**op, "type": "ok", "value": vals}
        raise ValueError(f"unknown f {op['f']!r}")


class YugabyteDB(jdb.DB, jdb.Process, jdb.Pause, jdb.LogFiles):
    """master + tserver daemons per node (yugabyte/db.clj)."""

    URL = ("https://downloads.yugabyte.com/releases/2.20.1.3/"
           "yugabyte-2.20.1.3-b3-linux-x86_64.tar.gz")
    DIR = "/opt/yugabyte"
    LOGS = ["/var/log/yb-master.log", "/var/log/yb-tserver.log"]

    def setup(self, test, node):
        cu.install_archive(self.URL, self.DIR)
        with c.su():
            c.exec_star(f"{self.DIR}/bin/post_install.sh || true")
        self.start(test, node)

    def start(self, test, node):
        masters = ",".join(f"{n}:7100" for n in test["nodes"])
        with c.su():
            cu.start_daemon(
                {"logfile": self.LOGS[0], "pidfile": "/var/run/yb-master.pid",
                 "chdir": self.DIR},
                f"{self.DIR}/bin/yb-master",
                "--master_addresses", masters,
                "--rpc_bind_addresses", f"{node}:7100",
                "--fs_data_dirs", "/var/lib/yb-master",
            )
            cu.start_daemon(
                {"logfile": self.LOGS[1],
                 "pidfile": "/var/run/yb-tserver.pid", "chdir": self.DIR},
                f"{self.DIR}/bin/yb-tserver",
                "--tserver_master_addrs", masters,
                "--rpc_bind_addresses", f"{node}:9100",
                "--fs_data_dirs", "/var/lib/yb-tserver",
                "--start_pgsql_proxy",
                "--pgsql_proxy_bind_address", "0.0.0.0:5433",
            )

    def kill(self, test, node):
        cu.grepkill("yb-tserver")
        cu.grepkill("yb-master")

    def pause(self, test, node):
        cu.grepkill("yb-tserver", signal="STOP")

    def resume(self, test, node):
        cu.grepkill("yb-tserver", signal="CONT")

    def teardown(self, test, node):
        self.kill(test, node)
        with c.su():
            c.exec("rm", "-rf", "/var/lib/yb-master", "/var/lib/yb-tserver")

    def log_files(self, test, node):
        return list(self.LOGS)


def bank_workload(opts: dict) -> dict:
    wl = wbank.test(opts)
    return {**wl, "client": BankClient()}


def append_workload(opts: dict) -> dict:
    wl = wa.test({"key_count": 4})
    return {"client": AppendClient(), "generator": wl["generator"],
            "checker": wl["checker"]}


def set_workload(opts: dict) -> dict:
    counter = [0]

    def add(test=None, ctx=None):
        counter[0] += 1
        return {"type": "invoke", "f": "add", "value": counter[0]}

    return {
        "client": SetClient(),
        "checker": jchecker.compose({
            "set": jchecker.set_checker(),
            "stats": jchecker.stats(),
        }),
        "generator": gen.clients(
            gen.limit(int(opts.get("ops") or 200), add)),
        "final-generator": gen.clients(
            gen.once({"type": "invoke", "f": "read", "value": None})),
    }


WORKLOADS = {"bank": bank_workload, "append": append_workload,
             "set": set_workload}


def test_fn(opts: dict) -> dict:
    """One cell of the workload × fault matrix (core.clj:73-161)."""
    name = opts.get("workload") or "append"
    wl = WORKLOADS[name](opts)
    db = YugabyteDB()
    raw_faults = opts.get("faults")
    if raw_faults is None:
        raw_faults = "partition,kill"
    faults = [f for f in raw_faults.split(",") if f]
    test = {
        "name": f"yugabyte-{name}-{'+'.join(faults) or 'none'}",
        "db": db,
        "net": jnet.iptables(),
    }
    if faults:
        pkg = ncombined.nemesis_package({
            "db": db,
            "interval": opts.get("nemesis_interval") or 10,
            "faults": faults,
        })
        test["nemesis"] = pkg["nemesis"]
        test["plot"] = {"nemeses": pkg["perf"]}
        phases = [
            gen.time_limit(
                opts.get("time_limit", 60),
                gen.nemesis(pkg["generator"], wl["generator"])),
            gen.nemesis(pkg["final-generator"]),
        ]
        if wl.get("final-generator") is not None:
            phases.append(wl["final-generator"])
        test["generator"] = gen.phases(*phases)
    else:
        test["generator"] = std_generator(
            opts, wl["generator"],
            final_client_gen=wl.get("final-generator"))
    test.update({k: v for k, v in wl.items()
                 if k not in ("generator", "final-generator")})
    return test


def matrix_test_fns(opts_base: dict | None = None) -> dict:
    """name -> test_fn closures for every workload × fault-set cell
    (yugabyte/core.clj:181-201 `test-all` combinatorics)."""
    fault_sets = ["partition", "kill", "partition,kill", ""]
    fns = {}
    for wname in WORKLOADS:
        for faults in fault_sets:
            label = f"{wname}-{faults.replace(',', '+') or 'none'}"

            def fn(opts, _w=wname, _f=faults):
                return test_fn({**opts, "workload": _w, "faults": _f})

            fns[label] = fn
    return fns


def _add_opts(p):
    p.add_argument("--workload", choices=sorted(WORKLOADS),
                   default="append")
    p.add_argument("--faults", default="partition,kill")
    p.add_argument("--nemesis-interval", type=int, default=10)
    p.add_argument("--ops", type=int, default=200)


def main(argv=None):
    cmds = dict(cli.single_test_cmd(test_fn, add_opts=_add_opts))
    cmds.update(cli.test_all_cmd(matrix_test_fns(),
                                 add_opts=_add_opts))
    cli.main_exit(cmds, argv)


if __name__ == "__main__":
    main()
