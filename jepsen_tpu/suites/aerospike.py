"""Aerospike suite: set workload over ``aql`` on the node.

The reference's aerospike suite (aerospike/, 1286 LoC, SURVEY §2.6) runs
cas-register/counter/set workloads through the Java client with a custom
pause-capable nemesis. Aerospike's scriptable surface without a driver
is ``aql`` (its SQL-ish CLI), which covers the **set** workload exactly:
each add inserts one record keyed by the element, the final read scans
the set back, and the set / set-full checkers decide lost or stale
elements (checker.clj:237-288,458-589). The cas/counter workloads need
generation-guarded operate() calls the CLI doesn't expose; they are
covered framework-wide by the ignite/consul/etcd register suites.

The DB implements kill+pause (jdb.Process/jdb.Pause) so the combined
nemesis packages can exercise the crash-recovery behavior the reference
suite was built to probe (its nemesis SIGSTOPs asd).
"""

from __future__ import annotations

import json
from typing import Any, Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from .. import nemesis as jnemesis, net as jnet
from ..control import util as cu
from .. import control as c
from . import std_generator

NS = "test"
SET = "jepsen"


class AqlClient(jclient.Client):
    """add → INSERT one record per element; read → scan the whole set.

    aql output is parsed line-wise: SELECT prints one JSON-ish row per
    record; we store the element in a single integer bin ``v``."""

    def __init__(self, node: Any = None):
        self.node = node

    def open(self, test, node):
        return AqlClient(node)

    def _aql(self, test, stmt: str) -> str:
        def run(t, node):
            return c.exec_star(
                f"aql -c {c.escape(stmt)} -o json")

        return c.on_nodes(test, run, [self.node])[self.node]

    def invoke(self, test, op):
        if op["f"] == "add":
            v = int(op["value"])
            self._aql(test,
                      f"INSERT INTO {NS}.{SET} (PK, v) VALUES ('e{v}', {v})")
            return {**op, "type": "ok"}
        if op["f"] == "read":
            out = self._aql(test, f"SELECT v FROM {NS}.{SET}")
            vals = set()
            for group in _json_groups(out):
                for row in group:
                    if isinstance(row, dict) and "v" in row:
                        vals.add(int(row["v"]))
            return {**op, "type": "ok", "value": sorted(vals)}
        raise ValueError(f"unknown f {op['f']!r}")

    def close(self, test):
        pass


def _json_groups(out: str):
    """aql -o json prints one JSON array per statement (possibly with
    trailing status lines); yield each parsed array."""
    depth, start = 0, None
    for i, ch in enumerate(out):
        if ch == "[":
            if depth == 0:
                start = i
            depth += 1
        elif ch == "]":
            depth -= 1
            if depth == 0 and start is not None:
                try:
                    yield json.loads(out[start:i + 1])
                except json.JSONDecodeError:
                    pass
                start = None


class AerospikeDB(jdb.DB, jdb.Process, jdb.Pause, jdb.LogFiles):
    LOG = "/var/log/aerospike/aerospike.log"

    def setup(self, test, node):
        from ..os_ import debian

        debian.install(["aerospike-server-community", "aerospike-tools"])
        self.start(test, node)

    def start(self, test, node):
        with c.su():
            c.exec("service", "aerospike", "start")

    def kill(self, test, node):
        cu.grepkill("asd")

    def pause(self, test, node):
        cu.grepkill("asd", signal="STOP")

    def resume(self, test, node):
        cu.grepkill("asd", signal="CONT")

    def teardown(self, test, node):
        with c.su():
            c.exec("service", "aerospike", "stop")
            c.exec_star("rm -rf /opt/aerospike/data/*")

    def log_files(self, test, node):
        return [self.LOG]


def set_workload(opts: Optional[dict] = None) -> dict:
    """Unique adds + a final read, checked with set-full (stale/lost
    element timelines + latencies) and the basic set checker."""
    o = dict(opts or {})
    counter = [0]

    def add(test=None, ctx=None):
        counter[0] += 1
        return {"type": "invoke", "f": "add", "value": counter[0]}

    load = gen.clients(gen.limit(int(o.get("ops") or 200), add))
    final_read = gen.clients(gen.once({"type": "invoke", "f": "read",
                                       "value": None}))
    return {
        "client": AqlClient(),
        "checker": jchecker.compose({
            "set": jchecker.set_checker(),
            "set-full": jchecker.set_full(),
            "stats": jchecker.stats(),
        }),
        "generator": gen.phases(load, final_read),
        "load-generator": load,
        "final-generator": final_read,
    }


def test_fn(opts: dict) -> dict:
    wl = set_workload(opts)
    db = AerospikeDB()
    return {
        "name": "aerospike-set",
        "db": db,
        "net": jnet.iptables(),
        "nemesis": jnemesis.hammer_time("asd"),
        **{k: v for k, v in wl.items()
           if k not in ("generator", "load-generator", "final-generator")},
        "generator": std_generator(
            opts, wl["load-generator"],
            final_client_gen=wl["final-generator"]),
    }


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn), argv)


if __name__ == "__main__":
    main()
