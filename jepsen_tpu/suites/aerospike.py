"""Aerospike suite: set / cas-register / counter workloads.

The reference's aerospike suite (aerospike/, 1286 LoC, SURVEY §2.6) runs
cas-register/counter/set workloads through the Java client with a custom
pause-capable nemesis. The **set** workload rides ``aql`` (aerospike's
SQL-ish CLI): each add inserts one record keyed by the element, the
final read scans the set back, and the set / set-full checkers decide
lost or stale elements (checker.clj:237-288,458-589).

The **cas-register** (cas_register.clj:42-106) and **counter**
(counter.clj:43-79) workloads need generation-guarded client calls aql
cannot script, so they speak to a node-side bridge daemon
(resources/as_bridge.py, the hz_bridge.py pattern) that runs the
official python client on the DB node: CAS is a linearized fetch +
EXPECT_GEN_EQUAL write exactly like support.clj's cas! (:425-439), and
the bridge's MISS/GEN/not-found replies map to the reference's
definite :fail errors (support.clj with-errors :value-mismatch /
:generation-mismatch / :not-found) while socket faults on mutations map
to :info.

The DB implements kill+pause (jdb.Process/jdb.Pause) so the combined
nemesis packages can exercise the crash-recovery behavior the reference
suite was built to probe (its nemesis SIGSTOPs asd).
"""

from __future__ import annotations

import json
import socket
from typing import Any, Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from .. import independent as jind
from .. import models as jmodels
from .. import nemesis as jnemesis, net as jnet
from ..checker.timeline import html as timeline_html
from ..control import util as cu
from .. import control as c
from . import std_generator
from ._bridge import BridgeClient, LineProto

NS = "test"
SET = "jepsen"
BRIDGE_PORT = 5601


class AqlClient(jclient.Client):
    """add → INSERT one record per element; read → scan the whole set.

    aql output is parsed line-wise: SELECT prints one JSON-ish row per
    record; we store the element in a single integer bin ``v``."""

    def __init__(self, node: Any = None):
        self.node = node

    def open(self, test, node):
        return AqlClient(node)

    def _aql(self, test, stmt: str) -> str:
        def run(t, node):
            return c.exec_star(
                f"aql -c {c.escape(stmt)} -o json")

        return c.on_nodes(test, run, [self.node])[self.node]

    def invoke(self, test, op):
        if op["f"] == "add":
            v = int(op["value"])
            self._aql(test,
                      f"INSERT INTO {NS}.{SET} (PK, v) VALUES ('e{v}', {v})")
            return {**op, "type": "ok"}
        if op["f"] == "read":
            out = self._aql(test, f"SELECT v FROM {NS}.{SET}")
            vals = set()
            for group in _json_groups(out):
                for row in group:
                    if isinstance(row, dict) and "v" in row:
                        vals.add(int(row["v"]))
            return {**op, "type": "ok", "value": sorted(vals)}
        raise ValueError(f"unknown f {op['f']!r}")

    def close(self, test):
        pass


def _json_groups(out: str):
    """aql -o json prints one JSON array per statement (possibly with
    trailing status lines); yield each parsed array."""
    depth, start = 0, None
    for i, ch in enumerate(out):
        if ch == "[":
            if depth == 0:
                start = i
            depth += 1
        elif ch == "]":
            depth -= 1
            if depth == 0 and start is not None:
                try:
                    yield json.loads(out[start:i + 1])
                except json.JSONDecodeError:
                    pass
                start = None


class AsBridge(LineProto):
    """Bridge connection to resources/as_bridge.py (replies may carry
    one JSON payload token)."""

    def __init__(self, host: str, port: Optional[int] = None,
                 timeout: float = 10.0):
        super().__init__(host, BRIDGE_PORT if port is None else port,
                         timeout=timeout)

    def cmd(self, *parts: Any) -> list:
        return self.roundtrip(parts, maxsplit=1)


def _j(v) -> str:
    """Compact JSON — the bridge splits its line on spaces."""
    return json.dumps(v, separators=(",", ":"))


class CasRegisterClient(BridgeClient):
    """Keyed CAS register over one ``value`` bin
    (cas_register.clj:42-77): read -> linearized GET; write -> PUT; cas
    -> the bridge's fetch + EXPECT_GEN_EQUAL write. Error mapping
    mirrors support.clj's with-errors: MISS/GEN/not-found are definite
    :fail (the write cannot have landed); socket-fault mapping and
    connection teardown ride BridgeClient."""

    SET = "cats"
    PROTO = AsBridge

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "read":
                out = self._conn().cmd("GET", self.SET, k)
                val = None
                if out[0] == "OK":
                    val = json.loads(out[1])["bins"].get("value")
                return {**op, "type": "ok", "value": jind.tuple_(k, val)}
            if op["f"] == "write":
                self._conn().cmd("PUT", self.SET, k, _j({"value": v}))
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                expect, new = v
                out = self._conn().cmd("CAS", self.SET, k,
                                       _j(expect), _j(new))
                if out[0] == "OK":
                    return {**op, "type": "ok"}
                err = {"MISS": "value-mismatch", "GEN":
                       "generation-mismatch"}.get(out[0], out[0])
                return {**op, "type": "fail", "error": err}
            raise ValueError(f"unknown f {op['f']!r}")
        except RuntimeError as e:
            if "not-found" in str(e):  # cas on a missing record: definite
                return {**op, "type": "fail", "error": "not-found"}
            raise
        except (ConnectionError, OSError, socket.timeout) as e:
            return self._fault(op, e)


class CounterClient(BridgeClient):
    """Single-record counter (counter.clj:43-66): setup writes
    {value: 0}, add -> the bridge's increment, read -> linearized GET."""

    SET = "counters"
    KEY = "pounce"
    PROTO = AsBridge

    def setup(self, test):
        self._conn().cmd("PUT", self.SET, self.KEY, _j({"value": 0}))

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                out = self._conn().cmd("GET", self.SET, self.KEY)
                val = 0
                if out[0] == "OK":
                    val = json.loads(out[1])["bins"].get("value", 0)
                return {**op, "type": "ok", "value": val}
            if op["f"] == "add":
                self._conn().cmd("ADD", self.SET, self.KEY, "value",
                                 int(op["value"]))
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except (ConnectionError, OSError, socket.timeout) as e:
            return self._fault(op, e)


class AerospikeDB(jdb.DB, jdb.Process, jdb.Pause, jdb.LogFiles):
    LOG = "/var/log/aerospike/aerospike.log"

    BRIDGE = "/opt/aerospike-bridge/as_bridge.py"
    BRIDGE_LOG = "/var/log/as-bridge.log"
    BRIDGE_PID = "/var/run/as-bridge.pid"

    def setup(self, test, node):
        import os

        from ..os_ import debian

        debian.install(["aerospike-server-community", "aerospike-tools",
                        "python3", "python3-pip"])
        # Node-side bridge for the generation-guarded cas/counter calls
        # (the hz_bridge pattern; reference uses the Java client).
        with c.su():
            c.exec("mkdir", "-p", "/opt/aerospike-bridge")
            c.exec_star("pip3 install --break-system-packages aerospike || "
                        "pip3 install aerospike")
        c.upload(
            os.path.join(os.path.dirname(__file__), "..", "resources",
                         "as_bridge.py"),
            self.BRIDGE)
        self.start(test, node)

    def start(self, test, node):
        with c.su():
            c.exec("service", "aerospike", "start")
            cu.start_daemon(
                {"logfile": self.BRIDGE_LOG, "pidfile": self.BRIDGE_PID,
                 "chdir": "/opt/aerospike-bridge"},
                "python3", self.BRIDGE, "--port", BRIDGE_PORT,
            )

    def kill(self, test, node):
        cu.grepkill("asd")
        cu.grepkill("as_bridge")

    def pause(self, test, node):
        cu.grepkill("asd", signal="STOP")

    def resume(self, test, node):
        cu.grepkill("asd", signal="CONT")

    def teardown(self, test, node):
        cu.grepkill("as_bridge")
        with c.su():
            c.exec("service", "aerospike", "stop")
            c.exec_star("rm -rf /opt/aerospike/data/*")

    def log_files(self, test, node):
        return [self.LOG]


def set_workload(opts: Optional[dict] = None) -> dict:
    """Unique adds + a final read, checked with set-full (stale/lost
    element timelines + latencies) and the basic set checker."""
    o = dict(opts or {})
    counter = [0]

    def add(test=None, ctx=None):
        counter[0] += 1
        return {"type": "invoke", "f": "add", "value": counter[0]}

    load = gen.clients(gen.limit(int(o.get("ops") or 200), add))
    final_read = gen.clients(gen.once({"type": "invoke", "f": "read",
                                       "value": None}))
    return {
        "client": AqlClient(),
        "checker": jchecker.compose({
            "set": jchecker.set_checker(),
            "set-full": jchecker.set_full(),
            "stats": jchecker.stats(),
        }),
        "generator": gen.phases(load, final_read),
        "load-generator": load,
        "final-generator": final_read,
    }


def cas_register_workload(opts: Optional[dict] = None) -> dict:
    """Keyed CAS register: 10 threads/key, reserve 5 readers over a
    w/cas/cas mix, 100-200 ops/key (cas_register.clj:84-106)."""
    import itertools

    o = dict(opts or {})
    n_threads = int(o.get("threads-per-key") or o.get("threads_per_key")
                    or 10)
    per_key = int(o.get("ops-per-key") or o.get("ops_per_key") or 0)

    def r(test=None, ctx=None):
        return {"type": "invoke", "f": "read", "value": None}

    def w(test=None, ctx=None):
        return {"type": "invoke", "f": "write", "value": gen.rand_int(5)}

    def cas(test=None, ctx=None):
        return {"type": "invoke", "f": "cas",
                "value": [gen.rand_int(5), gen.rand_int(5)]}

    def fgen(k):
        lim = per_key or 100 + gen.rand_int(100)
        return gen.limit(lim, gen.reserve(5, r, gen.mix([w, cas, cas])))

    return {
        "client": CasRegisterClient(),
        "checker": jind.checker(jchecker.compose({
            "linear": jchecker.linearizable(
                model=jmodels.CasRegister(init=None)),
            "timeline": timeline_html(),
        })),
        "generator": jind.concurrent_generator(
            n_threads, itertools.count(), fgen),
    }


def counter_workload(opts: Optional[dict] = None) -> dict:
    """Increment-heavy counter: ~100 adds per read (counter.clj:67-79),
    checked with the counter bounds checker (checker.clj:310-355)."""
    o = dict(opts or {})

    def r(test=None, ctx=None):
        return {"type": "invoke", "f": "read", "value": None}

    def add(test=None, ctx=None):
        return {"type": "invoke", "f": "add", "value": 1}

    return {
        "client": CounterClient(),
        "checker": jchecker.compose({
            "counter": jchecker.counter(),
            "stats": jchecker.stats(),
        }),
        "generator": gen.clients(gen.limit(
            int(o.get("ops") or 500),
            gen.mix([add] * 100 + [r]))),
    }


WORKLOADS = {
    "set": set_workload,
    "cas-register": cas_register_workload,
    "counter": counter_workload,
}


def test_fn(opts: dict) -> dict:
    name = opts.get("workload") or "set"
    wl = WORKLOADS[name](opts)
    db = AerospikeDB()
    test = {
        "name": f"aerospike-{name}",
        "db": db,
        "net": jnet.iptables(),
        "nemesis": jnemesis.hammer_time("asd"),
        **{k: v for k, v in wl.items()
           if k not in ("generator", "load-generator", "final-generator")},
    }
    test["generator"] = std_generator(
        opts, wl.get("load-generator") or wl["generator"],
        final_client_gen=wl.get("final-generator"))
    return test


def _add_opts(p):
    p.add_argument("--workload", choices=sorted(WORKLOADS), default="set")
    p.add_argument("--ops", type=int, default=200)


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn, add_opts=_add_opts), argv)


if __name__ == "__main__":
    main()
