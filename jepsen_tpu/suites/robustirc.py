"""RobustIRC suite: unique channel-topic messages as a set test.

The reference (robustirc/src/jepsen/robustirc.clj, 239 LoC) drives a
Raft-replicated IRC network over its HTTP bridge: create a session,
send NICK/USER/JOIN, ``add`` posts ``TOPIC #jepsen :<n>`` (with a
client-message id for dedup), ``read`` streams the message log and
extracts the topic integers; checked with the set checker under
partition-random-halves.

Same layering here: a session client over the
``/robustirc/v1/session`` + ``/<sid>/message`` + ``/<sid>/messages``
wire shape (the reference talks TLS with a self-signed cert; the
protocol shape is identical over plain HTTP — the suite takes a
``scheme`` option), a go-get + start-stop-daemon DB lifecycle with the
reference's primary-first singlenode bootstrap then join
(robustirc.clj:44-80), and the set workload with a final read.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import ssl
import urllib.request
from typing import Any, Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from .. import nemesis as jnemesis, net as jnet
from ..control import util as cu
from .. import control as c
from . import std_generator

PORT = 13001
# The real network talks TLS with a self-signed cert (the reference
# posts with :insecure? true); stubs speak the same protocol over
# plain http. --scheme selects.
SCHEME = "https"
CHANNEL = "#jepsen"

# Unique nick per session: nicks are global IRC state, so two clients
# on one node (concurrency > nodes, or re-open after a process crash)
# must never collide — a NICK rejection silently voids every later
# TOPIC post.
_NICKS = itertools.count(1)


class RobustSession:
    """One bridge session (robustirc.clj:103-136)."""

    def __init__(self, host: str, port: Optional[int] = None,
                 timeout: float = 10.0, scheme: Optional[str] = None):
        if port is None:
            port = PORT
        scheme = scheme or SCHEME
        self.base = f"{scheme}://{host}:{port}/robustirc/v1"
        self.timeout = timeout
        # Self-signed cert: verification off, like the reference's
        # :insecure? true (robustirc.clj:105-110).
        self.ctx = ssl._create_unverified_context() \
            if scheme == "https" else None
        res = self._post("/session", {}, auth=None)
        self.sid = res["Sessionid"]
        self.auth = res["Sessionauth"]

    def _open(self, req):
        return urllib.request.urlopen(req, timeout=self.timeout,
                                      context=self.ctx)

    def _post(self, path: str, body: dict, auth: Optional[str]) -> dict:
        req = urllib.request.Request(
            self.base + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json",
                     **({"X-Session-Auth": auth} if auth else {})},
            method="POST")
        with self._open(req) as r:
            out = r.read().decode()
        return json.loads(out) if out else {}

    def post_message(self, ircmessage: str) -> None:
        # Client-message id: random-ish but content-derived, the
        # server's dedup key (robustirc.clj:112-120).
        msgid = int(hashlib.md5(ircmessage.encode()).hexdigest()[17:][:15],
                    16) & 0x7FFFFFFF
        self._post(f"/{self.sid}/message",
                   {"Data": ircmessage, "ClientMessageId": msgid},
                   auth=self.auth)

    def read_messages(self) -> list:
        req = urllib.request.Request(
            f"{self.base}/{self.sid}/messages?lastseen=0.0",
            headers={"X-Session-Auth": self.auth})
        with self._open(req) as r:
            body = r.read().decode()
        # Stream of newline-separated JSON message objects.
        return [json.loads(line) for line in body.splitlines() if line]


def filter_topic(msg: dict) -> bool:
    parts = (msg.get("Data") or "").split(" ")
    return len(parts) > 1 and parts[1] == "TOPIC"


def extract_topic(msg: dict) -> int:
    return int((msg.get("Data") or "").rsplit(":", 1)[-1])


class SetClient(jclient.Client):
    """add -> TOPIC #jepsen :<n>; read -> all topic ints seen
    (robustirc.clj:150-180)."""

    def __init__(self, session: Optional[RobustSession] = None,
                 scheme: Optional[str] = None):
        self.session = session
        self.scheme = scheme

    def open(self, test, node):
        s = RobustSession(str(node), scheme=self.scheme)
        s.post_message(f"NICK j{os.getpid() % 100000}x{next(_NICKS)}")
        s.post_message("USER j j j j")
        s.post_message(f"JOIN {CHANNEL}")
        return SetClient(s, self.scheme)

    def invoke(self, test, op):
        if op["f"] == "add":
            try:
                self.session.post_message(
                    f"TOPIC {CHANNEL} :{op['value']}")
            except OSError:
                return {**op, "type": "fail", "error": "node-failure"}
            return {**op, "type": "ok"}
        if op["f"] == "read":
            try:
                msgs = self.session.read_messages()
            except OSError:
                return {**op, "type": "fail", "error": "node-failure"}
            vals = sorted({extract_topic(m) for m in msgs
                           if filter_topic(m)})
            return {**op, "type": "ok", "value": vals}
        raise ValueError(f"unknown f {op['f']!r}")

    def close(self, test):
        pass


class RobustIrcDB(jdb.DB, jdb.Process, jdb.LogFiles):
    """go get + primary-first singlenode bootstrap, then joins
    (robustirc.clj:23-83)."""

    BIN = "/root/gocode/bin/robustirc"
    LOG = "/var/log/robustirc.log"
    PID = "/var/run/robustirc.pid"

    def setup(self, test, node):
        from ..os_ import debian

        debian.install(["golang-go", "mercurial"])
        c.exec_star("env GOPATH=/root/gocode go get -u "
                    "github.com/robustirc/robustirc || true")
        with c.su():
            c.exec("rm", "-rf", "/var/lib/robustirc")
            c.exec("mkdir", "-p", "/var/lib/robustirc")
        self.start(test, node, bootstrap=True)

    def start(self, test, node, bootstrap: bool = False):
        """Relaunch the daemon only — a restart after a nemesis kill
        must keep the node's Raft state and rejoin, never re-wipe or
        re-bootstrap (-singlenode is for the FIRST primary start only,
        robustirc.clj:44-80)."""
        primary = test["nodes"][0]
        common = [
            "-listen", f"{node}:{PORT}",
            "-network_password", "secret",
            "-network_name", "jepsen",
        ]
        if bootstrap and node == primary:
            extra = ["-singlenode"]
        else:
            join_to = primary if node != primary else \
                next((n for n in test["nodes"] if n != node), primary)
            extra = ["-join", f"{join_to}:{PORT}"]
        with c.su():
            cu.start_daemon(
                {"logfile": self.LOG, "pidfile": self.PID,
                 "chdir": "/var/lib/robustirc"},
                self.BIN, *common, *extra)

    def kill(self, test, node):
        cu.grepkill("robustirc")

    def teardown(self, test, node):
        cu.grepkill("robustirc")
        with c.su():
            c.exec("rm", "-rf", "/var/lib/robustirc", self.PID)

    def log_files(self, test, node):
        return [self.LOG]


def set_workload(opts: Optional[dict] = None) -> dict:
    o = dict(opts or {})
    counter = [0]

    def add(test=None, ctx=None):
        counter[0] += 1
        return {"type": "invoke", "f": "add", "value": counter[0]}

    return {
        "client": SetClient(scheme=o.get("scheme")),
        "checker": jchecker.compose({
            "set": jchecker.set_checker(),
            "stats": jchecker.stats(),
        }),
        "generator": gen.clients(
            gen.limit(int(o.get("ops") or 200), add)),
        "final-generator": gen.clients(
            gen.once({"type": "invoke", "f": "read", "value": None})),
    }


WORKLOADS = {"set": set_workload}


def test_fn(opts: dict) -> dict:
    wl = set_workload(opts)
    test = {
        "name": "robustirc-set",
        "db": RobustIrcDB(),
        "net": jnet.iptables(),
        "nemesis": jnemesis.partition_random_halves(),
        **{k: v for k, v in wl.items()
           if k not in ("generator", "final-generator")},
    }
    test["generator"] = std_generator(
        opts, wl["generator"],
        final_client_gen=wl.get("final-generator"))
    return test


def _add_opts(p):
    p.add_argument("--ops", type=int, default=200)
    p.add_argument("--scheme", choices=["http", "https"], default=None,
                   help="bridge scheme (default https, the real "
                        "network's self-signed TLS)")


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn, add_opts=_add_opts), argv)


if __name__ == "__main__":
    main()
