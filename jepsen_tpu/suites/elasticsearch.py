"""Elasticsearch set suite.

Mirrors the reference elasticsearch suite (elasticsearch/ 929 LoC:
set + dirty-read workloads): insert unique documents over the HTTP API,
then a final refresh + search counts survivors — the `set` checker
reports lost and never-acknowledged elements. Partitions are the classic
way Elasticsearch loses inserts.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from .. import nemesis as jnemesis, net as jnet
from ..control import util as cu
from .. import control as c
from . import std_generator

PORT = 9200
INDEX = "jepsen"


class SetClient(jclient.Client, jclient.Reusable):
    def __init__(self, base: Optional[str] = None, timeout: float = 10.0):
        self.base = base
        self.timeout = timeout

    def open(self, test, node):
        return SetClient(f"http://{node}:{PORT}", self.timeout)

    def _req(self, method: str, path: str, body: Optional[dict] = None):
        req = urllib.request.Request(
            self.base + path,
            data=None if body is None else json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method=method)
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read().decode() or "{}")

    def invoke(self, test, op):
        if op["f"] == "add":
            # wait_for makes the write durable enough to be acknowledged.
            self._req("PUT",
                      f"/{INDEX}/_doc/{op['value']}?refresh=wait_for",
                      {"v": op["value"]})
            return {**op, "type": "ok"}
        if op["f"] == "read":
            try:
                self._req("POST", f"/{INDEX}/_refresh")
                vals = []
                search_after = None
                while True:
                    body = {"query": {"match_all": {}},
                            "sort": [{"v": "asc"}], "size": 10000}
                    if search_after is not None:
                        body["search_after"] = search_after
                    res = self._req("GET", f"/{INDEX}/_search", body)
                    hits = res.get("hits", {}).get("hits", [])
                    if not hits:
                        break
                    vals.extend(h["_source"]["v"] for h in hits)
                    sort_vals = hits[-1].get("sort")
                    if len(hits) < 10000 or not sort_vals:
                        break
                    search_after = sort_vals
                return {**op, "type": "ok", "value": sorted(vals)}
            except Exception:
                return {**op, "type": "fail", "error": "http"}
        raise ValueError(f"unknown f {op['f']!r}")


class ElasticsearchDB(jdb.DB, jdb.Process, jdb.LogFiles):
    LOG = "/var/log/elasticsearch/jepsen.log"

    def setup(self, test, node):
        from ..os_ import debian

        debian.install(["elasticsearch"])
        hosts = json.dumps(test["nodes"])
        with c.su():
            c.exec_star(
                "cat > /etc/elasticsearch/elasticsearch.yml <<'JEPSEN_EOF'\n"
                "cluster.name: jepsen\n"
                f"node.name: {node}\n"
                "network.host: 0.0.0.0\n"
                f"discovery.seed_hosts: {hosts}\n"
                f"cluster.initial_master_nodes: {hosts}\n"
                "xpack.security.enabled: false\n"
                "JEPSEN_EOF")
        self.start(test, node)

    def start(self, test, node):
        with c.su():
            c.exec("service", "elasticsearch", "start")

    def kill(self, test, node):
        cu.grepkill("org.elasticsearch")

    def teardown(self, test, node):
        with c.su():
            c.exec_star("service elasticsearch stop || true")
            c.exec("rm", "-rf", "/var/lib/elasticsearch/nodes")

    def log_files(self, test, node):
        return [self.LOG]


class DirtyReadClient(jclient.Client, jclient.Reusable):
    """elasticsearch/dirty_read.clj:32-104: writes index a doc per
    value, reads GET it by id (can observe un-replicated state — the
    dirty read under test), strong-reads refresh then search
    everything."""

    def __init__(self, base: Optional[str] = None, timeout: float = 10.0):
        self.base = base
        self.timeout = timeout

    def open(self, test, node):
        return DirtyReadClient(f"http://{node}:{PORT}", self.timeout)

    def _req(self, method: str, path: str, body: Optional[dict] = None):
        req = urllib.request.Request(
            self.base + path,
            data=None if body is None else json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method=method)
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read().decode() or "{}")

    def invoke(self, test, op):
        f = op["f"]
        if f == "write":
            self._req("PUT", f"/{INDEX}/_doc/{op['value']}",
                      {"v": op["value"]})
            return {**op, "type": "ok"}
        if f == "read":
            try:
                res = self._req("GET", f"/{INDEX}/_doc/{op['value']}")
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    return {**op, "type": "fail", "error": "not-found"}
                raise
            if not res.get("found", True):
                return {**op, "type": "fail", "error": "not-found"}
            return {**op, "type": "ok",
                    "value": res.get("_source", {}).get("v", op["value"])}
        if f == "strong-read":
            self._req("POST", f"/{INDEX}/_refresh")
            # Paginated like SetClient.read: a bare size-10000 search
            # silently truncates past ES's max_result_window, turning
            # long runs into phantom "lost" writes.
            vals = set()
            search_after = None
            while True:
                body = {"query": {"match_all": {}},
                        "sort": [{"v": "asc"}], "size": 10000}
                if search_after is not None:
                    body["search_after"] = search_after
                res = self._req("GET", f"/{INDEX}/_search", body)
                hits = res.get("hits", {}).get("hits", [])
                if not hits:
                    break
                vals.update(h["_source"]["v"] for h in hits)
                sort_vals = hits[-1].get("sort")
                if len(hits) < 10000 or not sort_vals:
                    break
                search_after = sort_vals
            return {**op, "type": "ok", "value": sorted(vals)}
        raise ValueError(f"unknown f {f!r}")

    def close(self, test):
        pass


def dirty_read_checker() -> jchecker.Checker:
    """dirty_read.clj:106-156: a read must never observe a value that no
    strong read confirmed (dirty), every acked write must survive
    (lost), and the per-thread strong reads must agree."""
    from ..checker import checker_fn

    def chk(test, history, opts):
        writes, reads, strong = set(), set(), []
        for op in history:
            if not op.is_ok:
                continue
            if op.f == "write":
                writes.add(op.value)
            elif op.f == "read":
                reads.add(op.value)
            elif op.f == "strong-read":
                strong.append(set(op.value or []))
        if not strong:
            return {"valid": "unknown", "error": "no strong reads"}
        on_all = set.intersection(*strong)
        on_some = set.union(*strong)
        dirty = sorted(reads - on_some)
        lost = sorted(writes - on_some)
        some_lost = sorted(writes - on_all)
        nodes_agree = on_all == on_some
        return {
            "valid": bool(nodes_agree and not dirty and not lost),
            "nodes-agree": nodes_agree,
            "read-count": len(reads),
            "on-all-count": len(on_all),
            "on-some-count": len(on_some),
            "dirty": dirty,
            "lost": lost,
            "some-lost": some_lost,
        }

    return checker_fn(chk, "dirty-read")


def set_workload(opts: dict) -> dict:
    import itertools

    ids = itertools.count()

    def add(test=None, ctx=None):
        return {"type": "invoke", "f": "add", "value": next(ids)}

    return {
        "client": SetClient(),
        "checker": jchecker.compose({
            "set": jchecker.set_checker(),
            "stats": jchecker.stats(),
        }),
        "generator": gen.stagger(
            0.05, gen.limit(int(opts.get("ops") or 200), add)),
        "final-generator": gen.clients(gen.once(
            {"type": "invoke", "f": "read", "value": None})),
    }


def dirty_read_workload(opts: dict) -> dict:
    """dirty_read.clj:158-189's rw-gen: writers emit sequential ids,
    readers probe recently-written ones; a final per-thread strong
    read closes the run."""
    import itertools
    import threading
    from collections import deque

    last = deque(maxlen=16)
    lock = threading.Lock()
    ctr = itertools.count()

    def write(t=None, ctx=None):
        v = next(ctr)
        with lock:
            last.append(v)
        return {"type": "invoke", "f": "write", "value": v}

    def read(t=None, ctx=None):
        with lock:
            pool = list(last)
        v = pool[gen.rand_int(len(pool))] if pool else 0
        return {"type": "invoke", "f": "read", "value": v}

    return {
        "client": DirtyReadClient(),
        "checker": jchecker.compose({
            "dirty-read": dirty_read_checker(),
            "stats": jchecker.stats(),
        }),
        "generator": gen.stagger(0.02, gen.reserve(2, write, read)),
        "final-generator": gen.clients(gen.each_thread(
            {"type": "invoke", "f": "strong-read", "value": None})),
    }


WORKLOADS = {"set": set_workload, "dirty-read": dirty_read_workload}


def test_fn(opts: dict) -> dict:
    name = opts.get("workload") or "set"
    wl = WORKLOADS[name](opts)
    return {
        "name": f"elasticsearch-{name}",
        "db": ElasticsearchDB(),
        "net": jnet.iptables(),
        "nemesis": jnemesis.partition_random_halves(),
        **{k: v for k, v in wl.items()
           if k not in ("generator", "final-generator")},
        "generator": std_generator(
            opts, gen.clients(wl["generator"]),
            final_client_gen=wl.get("final-generator"), dt=10),
    }


def _add_opts(p):
    p.add_argument("--workload", choices=sorted(WORKLOADS),
                   default="set")
    p.add_argument("--ops", type=int, default=200)


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn, add_opts=_add_opts), argv)


if __name__ == "__main__":
    main()
