"""Elasticsearch set suite.

Mirrors the reference elasticsearch suite (elasticsearch/ 929 LoC:
set + dirty-read workloads): insert unique documents over the HTTP API,
then a final refresh + search counts survivors — the `set` checker
reports lost and never-acknowledged elements. Partitions are the classic
way Elasticsearch loses inserts.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from .. import nemesis as jnemesis, net as jnet
from ..control import util as cu
from .. import control as c
from . import std_generator

PORT = 9200
INDEX = "jepsen"


class SetClient(jclient.Client, jclient.Reusable):
    def __init__(self, base: Optional[str] = None, timeout: float = 10.0):
        self.base = base
        self.timeout = timeout

    def open(self, test, node):
        return SetClient(f"http://{node}:{PORT}", self.timeout)

    def _req(self, method: str, path: str, body: Optional[dict] = None):
        req = urllib.request.Request(
            self.base + path,
            data=None if body is None else json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method=method)
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read().decode() or "{}")

    def invoke(self, test, op):
        if op["f"] == "add":
            # wait_for makes the write durable enough to be acknowledged.
            self._req("PUT",
                      f"/{INDEX}/_doc/{op['value']}?refresh=wait_for",
                      {"v": op["value"]})
            return {**op, "type": "ok"}
        if op["f"] == "read":
            try:
                self._req("POST", f"/{INDEX}/_refresh")
                vals = []
                search_after = None
                while True:
                    body = {"query": {"match_all": {}},
                            "sort": [{"v": "asc"}], "size": 10000}
                    if search_after is not None:
                        body["search_after"] = search_after
                    res = self._req("GET", f"/{INDEX}/_search", body)
                    hits = res.get("hits", {}).get("hits", [])
                    if not hits:
                        break
                    vals.extend(h["_source"]["v"] for h in hits)
                    sort_vals = hits[-1].get("sort")
                    if len(hits) < 10000 or not sort_vals:
                        break
                    search_after = sort_vals
                return {**op, "type": "ok", "value": sorted(vals)}
            except Exception:
                return {**op, "type": "fail", "error": "http"}
        raise ValueError(f"unknown f {op['f']!r}")


class ElasticsearchDB(jdb.DB, jdb.Process, jdb.LogFiles):
    LOG = "/var/log/elasticsearch/jepsen.log"

    def setup(self, test, node):
        from ..os_ import debian

        debian.install(["elasticsearch"])
        hosts = json.dumps(test["nodes"])
        with c.su():
            c.exec_star(
                "cat > /etc/elasticsearch/elasticsearch.yml <<'JEPSEN_EOF'\n"
                "cluster.name: jepsen\n"
                f"node.name: {node}\n"
                "network.host: 0.0.0.0\n"
                f"discovery.seed_hosts: {hosts}\n"
                f"cluster.initial_master_nodes: {hosts}\n"
                "xpack.security.enabled: false\n"
                "JEPSEN_EOF")
        self.start(test, node)

    def start(self, test, node):
        with c.su():
            c.exec("service", "elasticsearch", "start")

    def kill(self, test, node):
        cu.grepkill("org.elasticsearch")

    def teardown(self, test, node):
        with c.su():
            c.exec_star("service elasticsearch stop || true")
            c.exec("rm", "-rf", "/var/lib/elasticsearch/nodes")

    def log_files(self, test, node):
        return [self.LOG]


def test_fn(opts: dict) -> dict:
    import itertools

    ids = itertools.count()

    def add(test=None, ctx=None):
        return {"type": "invoke", "f": "add", "value": next(ids)}

    return {
        "name": "elasticsearch-set",
        "db": ElasticsearchDB(),
        "net": jnet.iptables(),
        "nemesis": jnemesis.partition_random_halves(),
        "client": SetClient(),
        "checker": jchecker.compose({
            "set": jchecker.set_checker(),
            "stats": jchecker.stats(),
        }),
        "generator": std_generator(
            opts, gen.clients(gen.stagger(0.05, add)),
            final_client_gen=gen.clients(
                gen.once({"type": "invoke", "f": "read", "value": None})),
            dt=10),
    }


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn), argv)


if __name__ == "__main__":
    main()
