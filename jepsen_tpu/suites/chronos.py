"""Chronos suite: job-scheduler run verification.

The reference's chronos suite (chronos/, 847 LoC, SURVEY §2.6) is the
one suite whose checker is about TIME, not data: jobs are submitted with
(start, interval, count, epsilon, duration); each run appends a
timestamp to a per-job file on the node it ran on; the checker computes
every job's target windows ``[start + i*interval, +epsilon]`` and
verifies a run landed in each window that closed while the cluster was
obligated to run it.

This suite mirrors that shape:

- ``add-job`` POSTs an ISO8601 job to ``/v1/scheduler/iso8601`` whose
  command appends ``date +%s.%N`` to ``/tmp/jepsen-chronos/<name>``;
- the final ``read`` collects every node's run files through the
  control session;
- :func:`run_checker` does the window analysis (chronos checker
  semantics, with the reference's allowance that the last window may
  still be open at read time)."""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from ..checker import Checker, checker_fn
from ..control import util as cu
from .. import nemesis as jnemesis, net as jnet
from .. import control as c
from . import std_generator

PORT = 4400
RUN_DIR = "/tmp/jepsen-chronos"


class ChronosClient(jclient.Client):
    """add-job over the REST API; read over the control session."""

    def __init__(self, node: Any = None):
        self.node = node

    def open(self, test, node):
        return ChronosClient(node)

    def invoke(self, test, op):
        if op["f"] == "add-job":
            spec = op["value"]
            name = f"jepsen-{spec['name']}"
            job = {
                "name": name,
                # R<count>/<start>/PT<interval>S — ISO8601 repeating.
                "schedule": (f"R{spec['count']}/{spec['start_iso']}/"
                             f"PT{spec['interval']}S"),
                "epsilon": f"PT{spec['epsilon']}S",
                "command": (f"mkdir -p {RUN_DIR} && "
                            f"date +%s.%N >> {RUN_DIR}/{name} && "
                            f"sleep {spec['duration']}"),
                "owner": "jepsen@jepsen.io",
            }
            req = urllib.request.Request(
                f"http://{self.node}:{PORT}/v1/scheduler/iso8601",
                data=json.dumps(job).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            try:
                with urllib.request.urlopen(req, timeout=10.0):
                    pass
            except urllib.error.HTTPError as e:
                # The scheduler answered: a definite rejection.
                return {**op, "type": "fail", "error": f"http-{e.code}"}
            return {**op, "type": "ok"}
        if op["f"] == "read":
            # Collect every node's run files (the runs may have landed
            # on any node).
            runs: dict = {}

            def collect(t, node):
                try:
                    out = c.exec_star(
                        f"cd {RUN_DIR} 2>/dev/null && "
                        "grep -H . * 2>/dev/null || true")
                except c.RemoteError:
                    return ""
                return out

            outs = c.on_nodes(test, collect, test.get("nodes"))
            for _node, out in outs.items():
                for line in (out or "").strip().split("\n"):
                    if ":" not in line:
                        continue
                    fname, ts = line.split(":", 1)
                    try:
                        runs.setdefault(
                            fname.replace("jepsen-", "", 1), []).append(
                            float(ts))
                    except ValueError:
                        continue
            import time as _t

            return {**op, "type": "ok",
                    "value": {"runs": {k: sorted(v)
                                       for k, v in runs.items()},
                              "read-time": _t.time()}}
        raise ValueError(f"unknown f {op['f']!r}")

    def close(self, test):
        pass


class ChronosDB(jdb.DB, jdb.Process, jdb.LogFiles):
    """chronos + zookeeper + mesos master/agent (chronos/src/jepsen/
    chronos.clj provisioning, abbreviated to the service layer)."""

    LOG = "/var/log/chronos.log"

    def setup(self, test, node):
        from ..os_ import debian

        debian.install(["zookeeperd", "mesos", "chronos"])
        self.start(test, node)

    def start(self, test, node):
        with c.su():
            c.exec("service", "zookeeper", "start")
            c.exec("service", "mesos-master", "start")
            c.exec("service", "mesos-slave", "start")
            c.exec("service", "chronos", "start")

    def kill(self, test, node):
        cu.grepkill("chronos")

    def teardown(self, test, node):
        with c.su():
            for svc in ("chronos", "mesos-slave", "mesos-master",
                        "zookeeper"):
                c.exec_star(f"service {svc} stop || true")
            c.exec_star(f"rm -rf {RUN_DIR}")

    def log_files(self, test, node):
        return [self.LOG]


def run_checker() -> Checker:
    """Window analysis: for each acked job, every target window
    ``[start + i*interval, start + i*interval + epsilon + duration]``
    that closed before the read must contain at least one run; runs
    outside every window are unexpected (chronos checker semantics)."""

    def chk(test, history, opts):
        jobs = {}
        read_time = None
        runs = {}
        for op in history:
            if op.f == "add-job" and op.is_ok:
                jobs[op.value["name"]] = op.value
            elif op.f == "read" and op.is_ok:
                v = op.value or {}
                runs = v.get("runs") or {}
                read_time = v.get("read-time")
        if read_time is None:
            # Fall back to the latest observed run.
            all_ts = [t for ts in runs.values() for t in ts]
            read_time = max(all_ts) if all_ts else 0.0
        bad_jobs = {}
        unexpected = {}
        for name, spec in jobs.items():
            had = sorted(runs.get(str(name), []) or
                         runs.get(name, []))
            missing = []
            matched = set()
            for i in range(int(spec["count"])):
                t0 = spec["start"] + i * spec["interval"]
                t1 = t0 + spec["epsilon"] + spec.get("duration", 0)
                if t1 > read_time:
                    continue  # window still open at read time
                hit = next((r for r in had
                            if t0 <= r <= t1 and r not in matched), None)
                if hit is None:
                    missing.append([t0, t1])
                else:
                    matched.add(hit)
            extra = [r for r in had if r not in matched and not any(
                spec["start"] + i * spec["interval"] <= r <=
                spec["start"] + i * spec["interval"] + spec["epsilon"]
                + spec.get("duration", 0)
                for i in range(int(spec["count"])))]
            if missing:
                bad_jobs[name] = missing
            if extra:
                unexpected[name] = extra
        return {
            "valid": not bad_jobs,
            "job_count": len(jobs),
            "run_count": sum(len(v) for v in runs.values()),
            "missing_windows": bad_jobs,
            "unexpected_runs": unexpected,
        }

    return checker_fn(chk, "chronos-runs")


def job_workload(opts: Optional[dict] = None) -> dict:
    o = dict(opts or {})
    counter = [0]
    interval = int(o.get("interval") or 30)

    def add_job(test=None, ctx=None):
        import datetime
        import time as _t

        counter[0] += 1
        start = _t.time() + 5
        return {"type": "invoke", "f": "add-job", "value": {
            "name": counter[0],
            "start": start,
            "start_iso": datetime.datetime.fromtimestamp(
                start, datetime.timezone.utc).strftime(
                "%Y-%m-%dT%H:%M:%SZ"),
            "interval": interval,
            "count": int(o.get("count") or 5),
            "epsilon": int(o.get("epsilon") or 10),
            "duration": int(o.get("duration") or 1),
        }}

    load = gen.clients(gen.stagger(
        float(o.get("stagger") or 5.0),
        gen.limit(int(o.get("jobs") or 10), add_job)))
    final = gen.clients(gen.once({"type": "invoke", "f": "read",
                                  "value": None}))
    return {
        "client": ChronosClient(),
        "checker": jchecker.compose({
            "runs": run_checker(),
            "stats": jchecker.stats(),
        }),
        "generator": gen.phases(load, final),
        "load-generator": load,
        "final-generator": final,
    }


def test_fn(opts: dict) -> dict:
    wl = job_workload(opts)
    return {
        "name": "chronos-runs",
        "db": ChronosDB(),
        "net": jnet.iptables(),
        "nemesis": jnemesis.partition_random_halves(),
        **{k: v for k, v in wl.items()
           if k not in ("generator", "load-generator", "final-generator")},
        "generator": std_generator(
            opts, wl["load-generator"],
            final_client_gen=wl["final-generator"]),
    }


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn), argv)


if __name__ == "__main__":
    main()
