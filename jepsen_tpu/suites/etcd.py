"""etcd suite: KV register + list-append over the v3 JSON gateway.

The reference's etcd-shaped suites (raftis/, and etcd workloads embedded
in other suites) drive a consensus KV store through CAS primitives. This
suite speaks etcd's ``/v3/kv/{range,put,txn}`` JSON gateway (base64-coded
keys/values): registers use txn compare-on-value CAS; list-append txns do
read-modify-write guarded by ``mod_revision`` compares, giving a real
elle list-append workload over an off-the-shelf store.
"""

from __future__ import annotations

import base64
import json
import urllib.request
from typing import Any, Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from .. import independent, nemesis as jnemesis, net as jnet
from .. import txn as jtxn
from ..control import util as cu
from ..models import CasRegister
from ..workloads import append as wa
from .. import control as c
from . import std_generator

PORT = 2379


def _b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


def _unb64(s: str) -> str:
    return base64.b64decode(s).decode()


class EtcdKV:
    """Minimal etcd v3 JSON gateway client."""

    def __init__(self, base: str, timeout: float = 5.0):
        self.base = base
        self.timeout = timeout

    def _post(self, path: str, body: dict) -> dict:
        req = urllib.request.Request(
            self.base + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read().decode())

    def get(self, k: str):
        """-> (value | None, mod_revision)."""
        res = self._post("/v3/kv/range", {"key": _b64(k)})
        kvs = res.get("kvs") or []
        if not kvs:
            return None, 0
        return _unb64(kvs[0]["value"]), int(kvs[0].get("mod_revision", 0))

    def put(self, k: str, v: str) -> None:
        self._post("/v3/kv/put", {"key": _b64(k), "value": _b64(v)})

    def cas_value(self, k: str, old: str, new: str) -> bool:
        """Txn: compare VALUE equals old -> put new."""
        res = self._post("/v3/kv/txn", {
            "compare": [{"key": _b64(k), "target": "VALUE",
                         "value": _b64(old), "result": "EQUAL"}],
            "success": [{"requestPut": {"key": _b64(k), "value": _b64(new)}}],
        })
        return bool(res.get("succeeded"))

    def cas_revision(self, k: str, mod_revision: int, new: str) -> bool:
        """Txn: compare MOD revision -> put (0 = key must not exist)."""
        return self.multi_txn({k: mod_revision}, {k: new})

    def multi_txn(self, guards: dict, puts: dict) -> bool:
        """One atomic txn: compare every key's mod_revision, then apply
        every put (0 = key must not exist)."""
        res = self._post("/v3/kv/txn", {
            "compare": [
                {"key": _b64(k), "target": "MOD",
                 "mod_revision": str(rev), "result": "EQUAL"}
                for k, rev in guards.items()
            ],
            "success": [
                {"requestPut": {"key": _b64(k), "value": _b64(v)}}
                for k, v in puts.items()
            ],
        })
        return bool(res.get("succeeded"))


class RegisterClient(jclient.Client, jclient.Reusable):
    """Keyed CAS register via value-compare txns."""

    def __init__(self, kv: Optional[EtcdKV] = None):
        self.kv = kv

    def open(self, test, node):
        return RegisterClient(EtcdKV(f"http://{node}:{PORT}"))

    def invoke(self, test, op):
        kv = op["value"]
        k, value = (kv.key, kv.value) if independent.is_tuple(kv) else (
            "r", kv)
        key = f"jepsen/{k}"
        f = op["f"]
        try:
            if f == "read":
                raw, _rev = self.kv.get(key)
                v = None if raw is None else json.loads(raw)
                return {**op, "type": "ok", "value": independent.KV(k, v)}
            if f == "write":
                self.kv.put(key, json.dumps(value))
                return {**op, "type": "ok"}
            if f == "cas":
                old, new = value
                ok = self.kv.cas_value(key, json.dumps(old), json.dumps(new))
                return {**op, "type": "ok" if ok else "fail"}
            raise ValueError(f"unknown f {f!r}")
        except Exception:
            if f == "read":
                return {**op, "type": "fail", "error": "http"}
            raise


class AppendClient(jclient.Client, jclient.Reusable):
    """List-append txns as optimistic STM over etcd: snapshot every
    touched key (value + mod_revision), evaluate the whole txn locally,
    then commit one atomic etcd txn guarding ALL touched keys' revisions
    and writing every appended key. A failed guard retries from a fresh
    snapshot; exhausted retries are a clean :fail (nothing committed)."""

    RETRIES = 16

    def __init__(self, kv: Optional[EtcdKV] = None):
        self.kv = kv

    def open(self, test, node):
        return AppendClient(EtcdKV(f"http://{node}:{PORT}"))

    def invoke(self, test, op):
        keys = {f"jepsen/append/{k}" for _f, k, _v in op["value"]}
        for _ in range(self.RETRIES):
            snap = {}
            for key in sorted(keys):
                raw, rev = self.kv.get(key)
                snap[key] = ([] if raw is None else json.loads(raw), rev)
            local = {k: list(v) for k, (v, _r) in snap.items()}
            done = []
            dirty = set()
            for f, k, v in op["value"]:
                key = f"jepsen/append/{k}"
                if f == "r":
                    done.append([f, k, list(local[key])])
                else:
                    local[key].append(v)
                    dirty.add(key)
                    done.append([f, k, v])
            guards = {k: rev for k, (_v, rev) in snap.items()}
            puts = {k: json.dumps(local[k]) for k in dirty}
            # Read-only txns still run the compare-only txn: the
            # per-key range snapshots aren't atomic on their own.
            if self.kv.multi_txn(guards, puts):
                return {**op, "type": "ok", "value": done}
        return {**op, "type": "fail", "error": "txn-contention"}


class EtcdDB(jdb.DB, jdb.Process, jdb.LogFiles):
    DIR = "/opt/etcd"
    LOG = "/var/log/etcd.log"
    PID = "/var/run/etcd.pid"

    def __init__(self, version: str = "3.5.9"):
        self.version = version

    def setup(self, test, node):
        url = (f"https://github.com/etcd-io/etcd/releases/download/"
               f"v{self.version}/etcd-v{self.version}-linux-amd64.tar.gz")
        cu.install_archive(url, self.DIR)
        self.start(test, node)

    def start(self, test, node):
        nodes = test["nodes"]
        cluster = ",".join(f"{n}=http://{n}:2380" for n in nodes)
        with c.su():
            cu.start_daemon(
                {"logfile": self.LOG, "pidfile": self.PID, "chdir": self.DIR},
                f"{self.DIR}/etcd",
                "--name", node,
                "--listen-client-urls", f"http://0.0.0.0:{PORT}",
                "--advertise-client-urls", f"http://{node}:{PORT}",
                "--listen-peer-urls", "http://0.0.0.0:2380",
                "--initial-advertise-peer-urls", f"http://{node}:2380",
                "--initial-cluster", cluster,
                "--data-dir", "/var/lib/etcd",
            )

    def kill(self, test, node):
        cu.grepkill("etcd")

    def teardown(self, test, node):
        cu.grepkill("etcd")
        with c.su():
            c.exec("rm", "-rf", "/var/lib/etcd", self.PID)

    def log_files(self, test, node):
        return [self.LOG]


def register_workload(opts: dict) -> dict:
    import itertools

    def r(test=None, ctx=None):
        return {"type": "invoke", "f": "read", "value": None}

    def w(test=None, ctx=None):
        return {"type": "invoke", "f": "write", "value": gen.rand_int(5)}

    def cas(test=None, ctx=None):
        return {"type": "invoke", "f": "cas",
                "value": [gen.rand_int(5), gen.rand_int(5)]}

    return {
        "client": RegisterClient(),
        "generator": independent.concurrent_generator(
            2, itertools.count(),
            lambda k: gen.limit(20, gen.mix([r, w, cas]))),
        "checker": independent.checker(
            jchecker.linearizable(model=CasRegister(init=None))),
    }


def append_workload(opts: dict) -> dict:
    wl = wa.test({"key_count": 4})
    return {"client": AppendClient(), "generator": wl["generator"],
            "checker": wl["checker"]}


WORKLOADS = {"register": register_workload, "append": append_workload}


def test_fn(opts: dict) -> dict:
    wl = WORKLOADS[opts.get("workload") or "register"](opts)
    return {
        "name": f"etcd-{opts.get('workload') or 'register'}",
        "db": EtcdDB(str(opts.get("version") or "3.5.9")),
        "net": jnet.iptables(),
        "nemesis": jnemesis.partition_random_halves(),
        **wl,
        "generator": std_generator(opts, wl["generator"]),
    }


def _add_opts(p):
    p.add_argument("--workload", choices=sorted(WORKLOADS),
                   default="register")
    p.add_argument("--version", default="3.5.9")


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn, add_opts=_add_opts), argv)


if __name__ == "__main__":
    main()
