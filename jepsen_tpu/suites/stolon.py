"""Stolon suite: HA PostgreSQL (keeper/sentinel/proxy) list-append.

The reference's stolon suite (stolon/, 1062 LoC) runs elle append +
ledger workloads against stolon-managed PostgreSQL. The SQL surface is
plain Postgres, so the client is the postgres suite's psql list-append
client pointed at the local stolon proxy; what's suite-specific is the
DB lifecycle: an etcd store, then stolon-keeper / stolon-sentinel /
stolon-proxy daemons per node (stolon/src/jepsen/stolon/db.clj shape).
"""

from __future__ import annotations

from typing import Optional

from .. import cli, db as jdb, generator as gen
from .. import nemesis as jnemesis, net as jnet
from ..control import util as cu
from .postgres import PsqlClient
from ..workloads import append as wa
from .. import control as c
from . import std_generator

CLUSTER = "jepsen"
PROXY_PORT = 25432


class StolonDB(jdb.DB, jdb.Process, jdb.LogFiles):
    URL = ("https://github.com/sorintlab/stolon/releases/download/v0.17.0/"
           "stolon-v0.17.0-linux-amd64.tar.gz")
    ETCD_URL = ("https://github.com/etcd-io/etcd/releases/download/v3.5.9/"
                "etcd-v3.5.9-linux-amd64.tar.gz")
    DIR = "/opt/stolon"
    ETCD = "/opt/stolon-etcd"
    LOGS = ["/var/log/stolon-keeper.log", "/var/log/stolon-sentinel.log",
            "/var/log/stolon-proxy.log"]

    def setup(self, test, node):
        from ..os_ import debian

        debian.install(["postgresql"])
        # The distro package auto-starts a default cluster on 5432; stop
        # it — queries must go through stolon-proxy, not a stock local
        # Postgres (silent wrong-target verification otherwise).
        with c.su():
            c.exec_star("service postgresql stop || true")
        cu.install_archive(self.URL, self.DIR)
        cu.install_archive(self.ETCD_URL, self.ETCD)
        if node == test["nodes"][0]:
            with c.su():
                c.exec_star(
                    f"{self.DIR}/bin/stolonctl --cluster-name {CLUSTER} "
                    "--store-backend etcdv3 init -y || true")
        self.start(test, node)

    def start(self, test, node):
        nodes = test["nodes"]
        cluster = ",".join(f"{n}=http://{n}:2380" for n in nodes)
        store = ",".join(f"http://{n}:2379" for n in nodes)
        with c.su():
            cu.start_daemon(
                {"logfile": "/var/log/stolon-etcd.log",
                 "pidfile": "/var/run/stolon-etcd.pid", "chdir": self.ETCD},
                f"{self.ETCD}/etcd",
                "--name", node,
                "--listen-client-urls", "http://0.0.0.0:2379",
                "--advertise-client-urls", f"http://{node}:2379",
                "--listen-peer-urls", "http://0.0.0.0:2380",
                "--initial-advertise-peer-urls", f"http://{node}:2380",
                "--initial-cluster", cluster,
                "--data-dir", "/var/lib/stolon-etcd",
            )
            common = ["--cluster-name", CLUSTER,
                      "--store-backend", "etcdv3",
                      "--store-endpoints", store]
            cu.start_daemon(
                {"logfile": self.LOGS[0],
                 "pidfile": "/var/run/stolon-keeper.pid", "chdir": self.DIR},
                f"{self.DIR}/bin/stolon-keeper",
                "--uid", f"keeper_{test['nodes'].index(node)}",
                "--data-dir", "/var/lib/stolon",
                "--pg-listen-address", node,
                "--pg-su-username", "postgres",
                "--pg-repl-username", "repl",
                "--pg-repl-password", "repl",
                *common,
            )
            cu.start_daemon(
                {"logfile": self.LOGS[1],
                 "pidfile": "/var/run/stolon-sentinel.pid",
                 "chdir": self.DIR},
                f"{self.DIR}/bin/stolon-sentinel", *common,
            )
            cu.start_daemon(
                {"logfile": self.LOGS[2],
                 "pidfile": "/var/run/stolon-proxy.pid", "chdir": self.DIR},
                f"{self.DIR}/bin/stolon-proxy",
                "--listen-address", "0.0.0.0", "--port", PROXY_PORT,
                *common,
            )

    def kill(self, test, node):
        for p in ("stolon-proxy", "stolon-sentinel", "stolon-keeper",
                  "postgres"):
            cu.grepkill(p)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.grepkill("etcd")
        with c.su():
            c.exec_star("rm -rf /var/lib/stolon /var/lib/stolon-etcd")

    def log_files(self, test, node):
        return list(self.LOGS)


def test_fn(opts: dict) -> dict:
    wl = wa.test({"key_count": 4})
    return {
        "name": "stolon-append",
        "db": StolonDB(),
        "net": jnet.iptables(),
        "nemesis": jnemesis.partition_random_halves(),
        "client": PsqlClient(host="127.0.0.1", port=PROXY_PORT),
        "checker": wl["checker"],
        "generator": std_generator(opts, wl["generator"]),
    }


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn), argv)


if __name__ == "__main__":
    main()
