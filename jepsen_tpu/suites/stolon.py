"""Stolon suite: HA PostgreSQL (keeper/sentinel/proxy) list-append.

The reference's stolon suite (stolon/, 1062 LoC) runs elle append +
ledger workloads against stolon-managed PostgreSQL. The SQL surface is
plain Postgres, so the client is the postgres suite's psql list-append
client pointed at the local stolon proxy; what's suite-specific is the
DB lifecycle: an etcd store, then stolon-keeper / stolon-sentinel /
stolon-proxy daemons per node (stolon/src/jepsen/stolon/db.clj shape).
"""

from __future__ import annotations

from typing import Optional

from .. import cli, db as jdb, generator as gen
from .. import nemesis as jnemesis, net as jnet
from ..control import util as cu
from .postgres import PsqlClient
from ..workloads import append as wa
from .. import control as c
from . import std_generator

CLUSTER = "jepsen"
PROXY_PORT = 25432


class StolonDB(jdb.DB, jdb.Process, jdb.LogFiles):
    URL = ("https://github.com/sorintlab/stolon/releases/download/v0.17.0/"
           "stolon-v0.17.0-linux-amd64.tar.gz")
    ETCD_URL = ("https://github.com/etcd-io/etcd/releases/download/v3.5.9/"
                "etcd-v3.5.9-linux-amd64.tar.gz")
    DIR = "/opt/stolon"
    ETCD = "/opt/stolon-etcd"
    LOGS = ["/var/log/stolon-keeper.log", "/var/log/stolon-sentinel.log",
            "/var/log/stolon-proxy.log"]

    def setup(self, test, node):
        from ..os_ import debian

        debian.install(["postgresql"])
        # The distro package auto-starts a default cluster on 5432; stop
        # it — queries must go through stolon-proxy, not a stock local
        # Postgres (silent wrong-target verification otherwise).
        with c.su():
            c.exec_star("service postgresql stop || true")
        cu.install_archive(self.URL, self.DIR)
        cu.install_archive(self.ETCD_URL, self.ETCD)
        if node == test["nodes"][0]:
            with c.su():
                c.exec_star(
                    f"{self.DIR}/bin/stolonctl --cluster-name {CLUSTER} "
                    "--store-backend etcdv3 init -y || true")
        self.start(test, node)

    def start(self, test, node):
        nodes = test["nodes"]
        cluster = ",".join(f"{n}=http://{n}:2380" for n in nodes)
        store = ",".join(f"http://{n}:2379" for n in nodes)
        with c.su():
            cu.start_daemon(
                {"logfile": "/var/log/stolon-etcd.log",
                 "pidfile": "/var/run/stolon-etcd.pid", "chdir": self.ETCD},
                f"{self.ETCD}/etcd",
                "--name", node,
                "--listen-client-urls", "http://0.0.0.0:2379",
                "--advertise-client-urls", f"http://{node}:2379",
                "--listen-peer-urls", "http://0.0.0.0:2380",
                "--initial-advertise-peer-urls", f"http://{node}:2380",
                "--initial-cluster", cluster,
                "--data-dir", "/var/lib/stolon-etcd",
            )
            common = ["--cluster-name", CLUSTER,
                      "--store-backend", "etcdv3",
                      "--store-endpoints", store]
            cu.start_daemon(
                {"logfile": self.LOGS[0],
                 "pidfile": "/var/run/stolon-keeper.pid", "chdir": self.DIR},
                f"{self.DIR}/bin/stolon-keeper",
                "--uid", f"keeper_{test['nodes'].index(node)}",
                "--data-dir", "/var/lib/stolon",
                "--pg-listen-address", node,
                "--pg-su-username", "postgres",
                "--pg-repl-username", "repl",
                "--pg-repl-password", "repl",
                *common,
            )
            cu.start_daemon(
                {"logfile": self.LOGS[1],
                 "pidfile": "/var/run/stolon-sentinel.pid",
                 "chdir": self.DIR},
                f"{self.DIR}/bin/stolon-sentinel", *common,
            )
            cu.start_daemon(
                {"logfile": self.LOGS[2],
                 "pidfile": "/var/run/stolon-proxy.pid", "chdir": self.DIR},
                f"{self.DIR}/bin/stolon-proxy",
                "--listen-address", "0.0.0.0", "--port", PROXY_PORT,
                *common,
            )

    def kill(self, test, node):
        for p in ("stolon-proxy", "stolon-sentinel", "stolon-keeper",
                  "postgres"):
            cu.grepkill(p)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.grepkill("etcd")
        with c.su():
            c.exec_star("rm -rf /var/lib/stolon /var/lib/stolon-etcd")

    def log_files(self, test, node):
        return list(self.LOGS)


LEDGER_TABLE = "ledger"


class LedgerClient(PsqlClient):
    """stolon/ledger.clj:26-135: a simulated bank ledger, one row per
    transaction. Withdrawals require a non-negative resulting balance —
    the double-spend attack serializability must refuse. The
    balance-check + insert runs as ONE serializable psql transaction
    using psql's \\gset/\\if conditionals; APPLIED/REFUSED markers
    make the verdict parseable."""

    _ids = None

    def __init__(self, node=None, user: str = "postgres",
                 host: Optional[str] = None, port: Optional[int] = None):
        super().__init__(node, user, host, port)
        if LedgerClient._ids is None:
            import itertools
            import threading

            LedgerClient._ids = (itertools.count(1), threading.Lock())

    def setup(self, test):
        self._psql(test,
                   f"CREATE TABLE IF NOT EXISTS {LEDGER_TABLE} "
                   "(id int PRIMARY KEY, account int NOT NULL, "
                   "amount int NOT NULL);\n"
                   "CREATE INDEX IF NOT EXISTS i_account ON "
                   f"{LEDGER_TABLE} (account)")

    def invoke(self, test, op):
        account, amount = op["value"]
        ctr, lock = LedgerClient._ids
        with lock:
            row_id = next(ctr)
        if amount > 0:
            # Deposits are unconditional single inserts.
            try:
                self._psql(test,
                           f"INSERT INTO {LEDGER_TABLE} "
                           f"(id, account, amount) VALUES "
                           f"({row_id}, {account}, {amount})")
                return {**op, "type": "ok"}
            except c.RemoteError as e:
                if "could not serialize" in str(e) \
                        or "deadlock" in str(e):
                    return {**op, "type": "fail",
                            "error": "serialization"}
                raise
        script = (
            "BEGIN ISOLATION LEVEL SERIALIZABLE;\n"
            f"SELECT COALESCE(SUM(amount), 0) + ({amount}) >= 0 AS ok "
            f"FROM {LEDGER_TABLE} WHERE account = {account} \\gset\n"
            "\\if :ok\n"
            f"INSERT INTO {LEDGER_TABLE} (id, account, amount) VALUES "
            f"({row_id}, {account}, {amount});\n"
            "COMMIT;\n"
            "\\echo APPLIED\n"
            "\\else\n"
            "ROLLBACK;\n"
            "\\echo REFUSED\n"
            "\\endif"
        )
        try:
            out = self._psql(test, script)
        except c.RemoteError as e:
            if "could not serialize" in str(e) or "deadlock" in str(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise
        if "APPLIED" in out:
            return {**op, "type": "ok"}
        if "REFUSED" in out:
            return {**op, "type": "fail", "error": "insufficient-funds"}
        return {**op, "type": "info", "error": "no-verdict-marker"}


def ledger_checker():
    """ledger.clj:137-165's per-account audit, under the charitable
    reading of indeterminacy: deposits count when ok OR info,
    withdrawals only when ok. Any account that can reach a NEGATIVE
    balance was double-spent — the G2 anomaly made concrete. (The
    reference's check-account also flags positive balances; a positive
    remainder is just an unspent deposit, so only the sound negative
    check is kept.)"""
    from ..checker import checker_fn

    def chk(test, history, opts):
        by_acct: dict = {}
        for op in history:
            if op.f != "transfer" or op.type not in ("ok", "info"):
                continue
            account, amount = op.value
            if amount > 0 or op.type == "ok":
                by_acct[account] = by_acct.get(account, 0) + amount
        errs = [{"account": a, "balance": b}
                for a, b in sorted(by_acct.items()) if b < 0]
        return {"valid": not errs, "errors": errs,
                "accounts": len(by_acct)}

    return checker_fn(chk, "ledger")


def ledger_workload(opts: dict) -> dict:
    """ledger.clj:167-189: per-account funding then double-spend
    attempts (the rand-gen shape: small signed amounts, 16 per
    account)."""
    import itertools

    from .. import checker as jchecker
    from .. import independent

    def fgen(k):
        # The concurrent generator lifts values to (account, amount)
        # tuples — the inner op carries the amount alone.
        def xfer(t=None, ctx=None):
            return {"type": "invoke", "f": "transfer",
                    "value": gen.rand_int(5) - 3}

        return gen.stagger(0.02, gen.limit(16, xfer))

    return {
        "client": LedgerClient(host="127.0.0.1", port=PROXY_PORT),
        "generator": independent.concurrent_generator(
            2, itertools.count(), fgen),
        "checker": jchecker.compose({
            "ledger": ledger_checker(),
            "stats": jchecker.stats(),
        }),
    }


def append_workload(opts: dict) -> dict:
    wl = wa.test({"key_count": 4})
    return {"client": PsqlClient(host="127.0.0.1", port=PROXY_PORT),
            "checker": wl["checker"], "generator": wl["generator"]}


WORKLOADS = {"append": append_workload, "ledger": ledger_workload}


def test_fn(opts: dict) -> dict:
    name = opts.get("workload") or "append"
    wl = WORKLOADS[name](opts)
    return {
        "name": f"stolon-{name}",
        "db": StolonDB(),
        "net": jnet.iptables(),
        "nemesis": jnemesis.partition_random_halves(),
        **{k: v for k, v in wl.items() if k != "generator"},
        "generator": std_generator(opts, wl["generator"]),
    }


def _add_opts(p):
    p.add_argument("--workload", choices=sorted(WORKLOADS),
                   default="append")


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn, add_opts=_add_opts), argv)


if __name__ == "__main__":
    main()
