"""Dgraph suite: upsert + set workloads over the HTTP API, with tracing.

The reference's dgraph suite (dgraph/, 2599 LoC) runs
bank/delete/long-fork/register/sequential/set/upsert/wr workloads and is
the one suite with distributed tracing (OpenCensus → Jaeger,
dgraph/src/jepsen/dgraph/trace.clj:1-74). This suite drives the alpha
HTTP API directly:

- **upsert**: the distinctive dgraph test — concurrent upserts of the
  same ``email`` predicate must create at most ONE node per email
  (dgraph/src/jepsen/dgraph/upsert.clj); checked by a final per-email
  uid count.
- **set**: unique integer inserts + final read-all, checked with the set
  checker.

Client ops ride :mod:`jepsen_tpu.trace` spans (the trace.clj analogue):
pass ``trace=True`` in opts and every client call is recorded to a span
collector exported into the store directory.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any, Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from .. import nemesis as jnemesis, net as jnet, trace as jtrace
from ..checker import Checker, checker_fn
from ..control import util as cu
from .. import control as c
from . import std_generator

PORT = 8080


class Alpha:
    """Minimal dgraph alpha HTTP client (mutate / query / alter)."""

    def __init__(self, host: str, port: Optional[int] = None,
                 timeout: float = 10.0):
        if port is None:
            port = PORT
        self.base = f"http://{host}:{port}"
        self.timeout = timeout

    def _post(self, path: str, body: Any, ctype: str) -> dict:
        req = urllib.request.Request(
            self.base + path,
            data=body if isinstance(body, bytes) else json.dumps(
                body).encode(),
            headers={"Content-Type": ctype}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            res = json.loads(r.read().decode())
        if res.get("errors"):
            raise RuntimeError(json.dumps(res["errors"])[:500])
        return res

    def alter(self, schema: str) -> None:
        self._post("/alter", schema.encode(), "application/dql")

    def mutate_json(self, body: dict) -> dict:
        return self._post("/mutate?commitNow=true", body,
                          "application/json")

    def query(self, q: str) -> dict:
        return self._post("/query", q.encode(), "application/dql")


class UpsertClient(jclient.Client):
    """upsert(email) → at most one node may win; count(email) reads how
    many exist (upsert.clj semantics via an upsert block)."""

    def __init__(self, conn: Optional[Alpha] = None):
        self.conn = conn

    def open(self, test, node):
        return UpsertClient(Alpha(str(node)))

    def setup(self, test):
        self.conn.alter("email: string @index(exact) @upsert .")

    def invoke(self, test, op):
        if op["f"] == "upsert":
            email = f"{op['value']}@jepsen.io"
            q = f'{{ q(func: eq(email, "{email}")) {{ u as uid }} }}'
            try:
                res = self.conn.mutate_json({
                    "query": q,
                    "cond": "@if(eq(len(u), 0))",
                    "set": [{"email": email}],
                })
            except RuntimeError as e:
                if "abort" in str(e).lower() or "conflict" in str(e).lower():
                    return {**op, "type": "fail", "error": "aborted"}
                raise
            created = bool((res.get("data") or {}).get("uids"))
            return {**op, "type": "ok" if created else "fail",
                    **({} if created else {"error": "exists"})}
        if op["f"] == "count":
            email = f"{op['value']}@jepsen.io"
            res = self.conn.query(
                f'{{ q(func: eq(email, "{email}")) {{ uid }} }}')
            n = len((res.get("data") or {}).get("q") or [])
            return {**op, "type": "ok", "value": [op["value"], n]}
        raise ValueError(f"unknown f {op['f']!r}")

    def close(self, test):
        pass


class SetClient(jclient.Client):
    def __init__(self, conn: Optional[Alpha] = None):
        self.conn = conn

    def open(self, test, node):
        return SetClient(Alpha(str(node)))

    def setup(self, test):
        self.conn.alter("value: int @index(int) .")

    def invoke(self, test, op):
        if op["f"] == "add":
            self.conn.mutate_json({"set": [{"value": int(op["value"])}]})
            return {**op, "type": "ok"}
        if op["f"] == "read":
            try:
                res = self.conn.query(
                    "{ q(func: has(value)) { value } }")
            except Exception:
                return {**op, "type": "fail", "error": "http"}
            vals = sorted(r["value"]
                          for r in (res.get("data") or {}).get("q") or [])
            return {**op, "type": "ok", "value": vals}
        raise ValueError(f"unknown f {op['f']!r}")

    def close(self, test):
        pass


class DgraphDB(jdb.DB, jdb.Process, jdb.LogFiles):
    """zero + alpha daemons per node (dgraph/src/jepsen/dgraph/support.clj)."""

    URL = "https://github.com/dgraph-io/dgraph/releases/download/v23.1.0/dgraph-linux-amd64.tar.gz"
    DIR = "/opt/dgraph"
    LOGS = ["/var/log/dgraph-zero.log", "/var/log/dgraph-alpha.log"]

    def setup(self, test, node):
        cu.install_archive(self.URL, self.DIR)
        self.start(test, node)

    def start(self, test, node):
        nodes = test["nodes"]
        i = nodes.index(node) if node in nodes else 0
        peer = f"{nodes[0]}:5080"
        with c.su():
            cu.start_daemon(
                {"logfile": self.LOGS[0],
                 "pidfile": "/var/run/dgraph-zero.pid", "chdir": self.DIR},
                f"{self.DIR}/dgraph", "zero",
                "--my", f"{node}:5080",
                *( [] if i == 0 else ["--peer", peer] ),
                "--raft", f"idx={i + 1}",
                "--wal", "/var/lib/dgraph/zw",
            )
            cu.start_daemon(
                {"logfile": self.LOGS[1],
                 "pidfile": "/var/run/dgraph-alpha.pid", "chdir": self.DIR},
                f"{self.DIR}/dgraph", "alpha",
                "--my", f"{node}:7080",
                "--zero", peer,
                "--postings", "/var/lib/dgraph/p",
                "--wal", "/var/lib/dgraph/w",
                "--security", "whitelist=0.0.0.0/0",
            )

    def kill(self, test, node):
        cu.grepkill("dgraph")

    def teardown(self, test, node):
        cu.grepkill("dgraph")
        with c.su():
            c.exec("rm", "-rf", "/var/lib/dgraph")

    def log_files(self, test, node):
        return list(self.LOGS)


def upsert_checker() -> Checker:
    """Every final count must be ≤ 1 node per email; counts of 0 with an
    acked upsert are lost inserts (upsert.clj checker semantics)."""

    def chk(test, history, opts):
        acked = set()
        counts = {}
        for op in history:
            if op.f == "upsert" and op.is_ok:
                acked.add(op.value)
            elif op.f == "count" and op.is_ok:
                k, n = op.value
                counts[k] = max(counts.get(k, 0), n)
        dups = {k: n for k, n in counts.items() if n > 1}
        lost = sorted(k for k in acked if counts.get(k, 0) == 0 and counts)
        return {
            "valid": not dups and not lost,
            "acked_count": len(acked),
            "duplicates": dups,
            "lost": lost,
        }

    return checker_fn(chk, "upsert")


def upsert_workload(opts: Optional[dict] = None) -> dict:
    o = dict(opts or {})
    keys = int(o.get("keys") or 10)

    def upsert(test=None, ctx=None):
        return {"type": "invoke", "f": "upsert",
                "value": gen.rand_int(keys)}

    # A list is a generator running its elements in sequence; each
    # thread reads every email's final count.
    final = gen.clients(gen.each_thread(
        [{"type": "invoke", "f": "count", "value": k}
         for k in range(keys)]))
    return {
        "client": UpsertClient(),
        "checker": jchecker.compose({
            "upsert": upsert_checker(),
            "stats": jchecker.stats(),
        }),
        "generator": gen.clients(
            gen.limit(int(o.get("ops") or 200), upsert)),
        "final-generator": final,
    }


def set_workload(opts: Optional[dict] = None) -> dict:
    o = dict(opts or {})
    counter = [0]

    def add(test=None, ctx=None):
        counter[0] += 1
        return {"type": "invoke", "f": "add", "value": counter[0]}

    return {
        "client": SetClient(),
        "checker": jchecker.compose({
            "set": jchecker.set_checker(),
            "stats": jchecker.stats(),
        }),
        "generator": gen.clients(
            gen.limit(int(o.get("ops") or 200), add)),
        "final-generator": gen.clients(
            gen.once({"type": "invoke", "f": "read", "value": None})),
    }


WORKLOADS = {"upsert": upsert_workload, "set": set_workload}


def trace_export_checker(collector) -> Checker:
    """Writes spans.jsonl into the store directory at analysis time (the
    same store-side-effect seam timeline.html uses)."""

    def chk(test, history, opts):
        path = jtrace.store_spans(test, collector)
        return {"valid": True, "spans": len(collector.spans),
                "file": path}

    return checker_fn(chk, "trace")


def test_fn(opts: dict) -> dict:
    name = opts.get("workload") or "upsert"
    wl = WORKLOADS[name](opts)
    client = wl["client"]
    checker = wl["checker"]
    if opts.get("trace"):
        collector = jtrace.Collector()
        client = jtrace.tracing(client, collector)
        checker = jchecker.compose({
            "workload": checker,
            "trace": trace_export_checker(collector),
        })
    return {
        "name": f"dgraph-{name}",
        "db": DgraphDB(),
        "net": jnet.iptables(),
        "nemesis": jnemesis.partition_random_halves(),
        **{k: v for k, v in wl.items()
           if k not in ("generator", "final-generator", "client",
                        "checker")},
        "client": client,
        "checker": checker,
        "generator": std_generator(
            opts, wl["generator"],
            final_client_gen=wl.get("final-generator")),
    }


def _add_opts(p):
    p.add_argument("--workload", choices=sorted(WORKLOADS),
                   default="upsert")
    p.add_argument("--ops", type=int, default=200)
    p.add_argument("--keys", type=int, default=10)
    p.add_argument("--trace", action="store_true")


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn, add_opts=_add_opts), argv)


if __name__ == "__main__":
    main()
