"""Dgraph suite: the reference's full workload roster over the alpha
HTTP API, with tracing.

The reference's dgraph suite (dgraph/, 2599 LoC) runs
bank/delete/long-fork/linearizable-register/sequential/set/upsert/wr
workloads and is the one suite with distributed tracing (OpenCensus →
Jaeger, dgraph/src/jepsen/dgraph/trace.clj:1-74). This suite drives the
alpha HTTP API directly:

- **upsert**: the distinctive dgraph test — concurrent upserts of the
  same ``email`` predicate must create at most ONE node per email
  (dgraph/src/jepsen/dgraph/upsert.clj); checked by a final per-email
  uid count.
- **set**: unique integer inserts + final read-all (set.clj).
- **bank**: transfers with on-the-fly account create/delete
  (bank.clj:60-199; the 7-way predicate striping there is a sharding
  detail, collapsed to one predicate family here).
- **delete**: per-key upsert/delete/read index-consistency (delete.clj).
- **long-fork** / **wr**: micro-op txn client (client.clj txn-client
  analogue) under the long-fork and elle wr checkers — wr composes the
  realtime graph exactly like the reference (wr.clj:20-31).
- **linearizable-register** / **sequential**: keyed register CAS and the
  monotonic read/inc probe (linearizable_register.clj, sequential.clj).

Where the reference's JVM client wraps multi-step gRPC transactions,
every txn here is ONE upsert-block request (query blocks + conditional
mutations + commitNow) — atomic server-side, so the HTTP client needs
no txn-context plumbing.

Client ops ride :mod:`jepsen_tpu.trace` spans (the trace.clj analogue):
pass ``trace=True`` in opts and every client call is recorded to a span
collector exported into the store directory.
"""

from __future__ import annotations

import itertools
import json
import urllib.request
from typing import Any, Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from .. import independent
from .. import nemesis as jnemesis, net as jnet, trace as jtrace
from ..checker import Checker, checker_fn
from ..control import util as cu
from ..workloads import bank as wbank
from ..workloads import linearizable_register as wreg
from ..workloads import long_fork as wlf
from ..workloads import wr as wwr
from .. import control as c
from . import std_generator

PORT = 8080


class Alpha:
    """Minimal dgraph alpha HTTP client (mutate / query / alter)."""

    def __init__(self, host: str, port: Optional[int] = None,
                 timeout: float = 10.0):
        if port is None:
            port = PORT
        self.base = f"http://{host}:{port}"
        self.timeout = timeout

    def _post(self, path: str, body: Any, ctype: str) -> dict:
        req = urllib.request.Request(
            self.base + path,
            data=body if isinstance(body, bytes) else json.dumps(
                body).encode(),
            headers={"Content-Type": ctype}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            res = json.loads(r.read().decode())
        if res.get("errors"):
            raise RuntimeError(json.dumps(res["errors"])[:500])
        return res

    def alter(self, schema: str) -> None:
        self._post("/alter", schema.encode(), "application/dql")

    def mutate_json(self, body: dict) -> dict:
        return self._post("/mutate?commitNow=true", body,
                          "application/json")

    def query(self, q: str) -> dict:
        return self._post("/query", q.encode(), "application/dql")


class UpsertClient(jclient.Client):
    """upsert(email) → at most one node may win; count(email) reads how
    many exist (upsert.clj semantics via an upsert block)."""

    def __init__(self, conn: Optional[Alpha] = None):
        self.conn = conn

    def open(self, test, node):
        return UpsertClient(Alpha(str(node)))

    def setup(self, test):
        self.conn.alter("email: string @index(exact) @upsert .")

    def invoke(self, test, op):
        if op["f"] == "upsert":
            email = f"{op['value']}@jepsen.io"
            q = f'{{ q(func: eq(email, "{email}")) {{ u as uid }} }}'
            try:
                res = self.conn.mutate_json({
                    "query": q,
                    "cond": "@if(eq(len(u), 0))",
                    "set": [{"email": email}],
                })
            except RuntimeError as e:
                if "abort" in str(e).lower() or "conflict" in str(e).lower():
                    return {**op, "type": "fail", "error": "aborted"}
                raise
            created = bool((res.get("data") or {}).get("uids"))
            return {**op, "type": "ok" if created else "fail",
                    **({} if created else {"error": "exists"})}
        if op["f"] == "count":
            email = f"{op['value']}@jepsen.io"
            res = self.conn.query(
                f'{{ q(func: eq(email, "{email}")) {{ uid }} }}')
            n = len((res.get("data") or {}).get("q") or [])
            return {**op, "type": "ok", "value": [op["value"], n]}
        raise ValueError(f"unknown f {op['f']!r}")

    def close(self, test):
        pass


class SetClient(jclient.Client):
    def __init__(self, conn: Optional[Alpha] = None):
        self.conn = conn

    def open(self, test, node):
        return SetClient(Alpha(str(node)))

    def setup(self, test):
        self.conn.alter("value: int @index(int) .")

    def invoke(self, test, op):
        if op["f"] == "add":
            self.conn.mutate_json({"set": [{"value": int(op["value"])}]})
            return {**op, "type": "ok"}
        if op["f"] == "read":
            try:
                res = self.conn.query(
                    "{ q(func: has(value)) { value } }")
            except Exception:
                return {**op, "type": "fail", "error": "http"}
            vals = sorted(r["value"]
                          for r in (res.get("data") or {}).get("q") or [])
            return {**op, "type": "ok", "value": vals}
        raise ValueError(f"unknown f {op['f']!r}")

    def close(self, test):
        pass


def _is_conflict(e: Exception) -> bool:
    s = str(e).lower()
    return "abort" in s or "conflict" in s


def _kv_rows(res: dict, block: str = "q") -> list:
    """Query-block results: /query responses carry them directly under
    data; /mutate upsert-block responses nest them under
    data["queries"] (only "uids" sits at data's top level)."""
    data = res.get("data") or {}
    queries = data.get("queries")
    if isinstance(queries, dict):
        return queries.get(block) or []
    return data.get(block) or []


class _AlphaClient(jclient.Client):
    """Shared alpha-client shape: per-node connection and the
    conflict-as-definite-fail discipline (client.clj's
    with-conflict-as-fail — an aborted txn definitely did not commit).
    Subclasses implement ``_invoke``."""

    def __init__(self, conn: Optional[Alpha] = None):
        self.conn = conn

    def open(self, test, node):
        return type(self)(Alpha(str(node)))

    def invoke(self, test, op):
        try:
            return self._invoke(test, op)
        except RuntimeError as e:
            if _is_conflict(e):
                return {**op, "type": "fail", "error": "conflict"}
            raise

    def close(self, test):
        pass


class TxnClient(_AlphaClient):
    """Generic micro-op txn client (the reference's
    dgraph.client/txn-client, client.clj:430-471): value is
    ``[["r", k, v?], ["w", k, v], …]``. Reads become named query blocks,
    writes insert-or-update mutation pairs — the whole txn is one
    commitNow upsert block. ``blind_insert`` skips the update arm for
    workloads whose keys are written once (long-fork's
    blind-insert-on-write?, long_fork.clj:5-8)."""

    def __init__(self, conn: Optional[Alpha] = None,
                 blind_insert: bool = False):
        super().__init__(conn)
        self.blind_insert = blind_insert

    def open(self, test, node):
        return type(self)(Alpha(str(node)), self.blind_insert)

    def setup(self, test):
        self.conn.alter("key: int @index(int) @upsert .\nvalue: int .")

    def _invoke(self, test, op):
        mops = op["value"]
        # The upsert block's query and conditions all evaluate at the
        # txn snapshot, so intra-txn effects are resolved client-side:
        # reads after an own write return that write (read-your-writes),
        # and only the LAST write per key is sent (earlier ones could
        # otherwise each satisfy the len==0 insert arm and duplicate
        # the record).
        written: dict = {}
        local_reads: dict = {}
        qparts = []
        last_write: dict = {}
        for i, (f, k, v) in enumerate(mops):
            if f == "w":
                written[k] = v
                last_write[k] = i
            elif f == "r":
                if k in written:
                    local_reads[i] = written[k]
                else:
                    qparts.append(f"q{i}(func: eq(key, {k})) {{ value }}")
        muts = []
        for i, (f, k, v) in enumerate(mops):
            if f != "w" or last_write[k] != i:
                continue
            if self.blind_insert:
                muts.append({"set": [{"key": k, "value": v}]})
            else:
                qparts.append(f"u{i} as var(func: eq(key, {k}))")
                muts.append({"cond": f"@if(eq(len(u{i}), 0))",
                             "set": [{"key": k, "value": v}]})
                muts.append({"cond": f"@if(eq(len(u{i}), 1))",
                             "set": [{"uid": f"uid(u{i})", "value": v}]})
        q = "{ " + " ".join(qparts) + " }" if qparts else None
        if muts:
            body = {"mutations": muts}
            if q:
                body["query"] = q
            res = self.conn.mutate_json(body)
        else:
            res = self.conn.query(q)
        done = []
        for i, (f, k, v) in enumerate(mops):
            if f == "r":
                if i in local_reads:
                    done.append(["r", k, local_reads[i]])
                else:
                    rows = _kv_rows(res, f"q{i}")
                    done.append(
                        ["r", k, rows[0].get("value") if rows else None])
            else:
                done.append([f, k, v])
        return {**op, "type": "ok", "value": done}


class LinRegisterClient(_AlphaClient):
    """Keyed linearizable register (linearizable_register.clj:33-67):
    read/write/cas, each one upsert block. Read timeouts convert to
    :fail (reads are idempotent, linearizable_register.clj:24-31)."""

    def setup(self, test):
        self.conn.alter("key: int @index(int) @upsert .\nvalue: int .")

    def invoke(self, test, op):
        try:
            return super().invoke(test, op)
        except Exception:
            # Reads are idempotent: ANY error is safely a definite fail
            # (read-info->fail, linearizable_register.clj:24-31).
            if op["f"] == "read":
                return {**op, "type": "fail", "error": "read-error"}
            raise

    def _invoke(self, test, op):
        k, v = op["value"]
        if op["f"] == "read":
            res = self.conn.query(
                f"{{ q(func: eq(key, {k})) {{ uid value }} }}")
            rows = _kv_rows(res)
            val = rows[0].get("value") if rows else None
            return {**op, "type": "ok",
                    "value": independent.tuple_(k, val)}
        if op["f"] == "write":
            self.conn.mutate_json({
                "query": f"{{ u as var(func: eq(key, {k})) }}",
                "mutations": [
                    {"cond": "@if(eq(len(u), 0))",
                     "set": [{"key": k, "value": v}]},
                    {"cond": "@if(eq(len(u), 1))",
                     "set": [{"uid": "uid(u)", "value": v}]},
                ]})
            return {**op, "type": "ok"}
        old, new = v
        res = self.conn.mutate_json({
            "query": f"{{ q(func: eq(key, {k})) "
                     f"@filter(eq(value, {old})) {{ u as uid }} }}",
            "mutations": [
                {"cond": "@if(eq(len(u), 1))",
                 "set": [{"uid": "uid(u)", "value": new}]},
            ]})
        if _kv_rows(res):
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": "value-mismatch"}


class DeleteClient(_AlphaClient):
    """Keyed upsert/delete/read probing index freshness
    (delete.clj:23-62)."""

    def setup(self, test):
        self.conn.alter("key: int @index(int) @upsert .")

    def _invoke(self, test, op):
        k, _v = op["value"]
        if op["f"] == "read":
            res = self.conn.query(
                f"{{ q(func: eq(key, {k})) {{ uid key }} }}")
            return {**op, "type": "ok",
                    "value": independent.tuple_(k, _kv_rows(res))}
        if op["f"] == "upsert":
            res = self.conn.mutate_json({
                "query": f"{{ u as var(func: eq(key, {k})) }}",
                "mutations": [{"cond": "@if(eq(len(u), 0))",
                               "set": [{"key": k}]}]})
            created = bool((res.get("data") or {}).get("uids"))
            return {**op, "type": "ok" if created else "fail",
                    **({} if created else {"error": "present"})}
        res = self.conn.mutate_json({
            "query": f"{{ q(func: eq(key, {k})) {{ u as uid }} }}",
            "mutations": [{"cond": "@if(eq(len(u), 1))",
                           "delete": [{"uid": "uid(u)",
                                       "key": None}]}]})
        if _kv_rows(res):
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": "not-found"}


class BankClient(_AlphaClient):
    """Bank transfers with on-the-fly account create/delete
    (bank.clj:60-199): the reference's find/write/abort dance is one
    upsert block whose condition blocks encode sufficient funds
    (a filtered query block's len) and the create/delete cases."""

    def setup(self, test):
        self.conn.alter("key: int @index(int) @upsert .\n"
                        "type: string @index(exact) .\namount: int .")
        for acct, amt in wbank.initial_balances(test):
            self.conn.mutate_json({
                "query": f"{{ u as var(func: eq(key, {acct})) }}",
                "mutations": [{"cond": "@if(eq(len(u), 0))",
                               "set": [{"key": acct, "type": "account",
                                        "amount": amt}]}]})

    def _invoke(self, test, op):
        if op["f"] == "read":
            res = self.conn.query(
                '{ q(func: eq(type, "account")) { key amount } }')
            return {**op, "type": "ok",
                    "value": {r["key"]: r["amount"]
                              for r in _kv_rows(res)}}
        v = op["value"]
        f_, t_, amt = v["from"], v["to"], v["amount"]
        res = self.conn.mutate_json({
            "query": (
                # fa: the from-account, only if it can afford amt.
                f"{{ fa(func: eq(key, {f_})) "
                f"@filter(ge(amount, {amt})) "
                f"{{ fu as uid fv as amount nf as math(fv - {amt}) }} "
                # fz: from-account that lands exactly on zero.
                f"fz(func: eq(key, {f_})) "
                f"@filter(eq(amount, {amt})) {{ fzu as uid }} "
                f"tb(func: eq(key, {t_})) "
                f"{{ tu as uid tv as amount nt as math(tv + {amt}) }} }}"
            ),
            "mutations": [
                {"cond": "@if(eq(len(fu), 1) AND eq(len(fzu), 0))",
                 "set": [{"uid": "uid(fu)", "amount": "val(nf)"}]},
                # Zero balance: delete the account record entirely
                # (bank.clj:88-99).
                {"cond": "@if(eq(len(fzu), 1))",
                 "delete": [{"uid": "uid(fzu)", "key": None,
                             "type": None, "amount": None}]},
                {"cond": "@if(eq(len(fu), 1) AND eq(len(tu), 1))",
                 "set": [{"uid": "uid(tu)", "amount": "val(nt)"}]},
                # Destination doesn't exist yet: create it.
                {"cond": "@if(eq(len(fu), 1) AND eq(len(tu), 0))",
                 "set": [{"key": t_, "type": "account",
                          "amount": amt}]},
            ]})
        if _kv_rows(res, "fa"):
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": "insufficient-funds"}


class SequentialRegClient(_AlphaClient):
    """Keyed inc/read register for the monotonic-state probe
    (sequential.clj:63-105): inc reads value in the upsert block's query
    and writes val(math(v+1)) server-side."""

    def setup(self, test):
        self.conn.alter("key: int @index(int) @upsert .\nvalue: int .")

    def _invoke(self, test, op):
        k, _v = op["value"]
        if op["f"] == "read":
            res = self.conn.query(
                f"{{ q(func: eq(key, {k})) {{ value }} }}")
            rows = _kv_rows(res)
            val = rows[0].get("value", 0) if rows else 0
            return {**op, "type": "ok",
                    "value": independent.tuple_(k, val)}
        res = self.conn.mutate_json({
            "query": f"{{ q(func: eq(key, {k})) "
                     f"{{ u as uid v as value nv as math(v + 1) }} }}",
            "mutations": [
                {"cond": "@if(eq(len(u), 0))",
                 "set": [{"key": k, "value": 1}]},
                {"cond": "@if(eq(len(u), 1))",
                 "set": [{"uid": "uid(u)", "value": "val(nv)"}]},
            ]})
        rows = _kv_rows(res)
        new = (rows[0].get("value", 0) + 1) if rows else 1
        return {**op, "type": "ok", "value": independent.tuple_(k, new)}


class DgraphDB(jdb.DB, jdb.Process, jdb.LogFiles):
    """zero + alpha daemons per node (dgraph/src/jepsen/dgraph/support.clj)."""

    URL = "https://github.com/dgraph-io/dgraph/releases/download/v23.1.0/dgraph-linux-amd64.tar.gz"
    DIR = "/opt/dgraph"
    LOGS = ["/var/log/dgraph-zero.log", "/var/log/dgraph-alpha.log"]

    def setup(self, test, node):
        cu.install_archive(self.URL, self.DIR)
        self.start(test, node)

    def start(self, test, node):
        nodes = test["nodes"]
        i = nodes.index(node) if node in nodes else 0
        peer = f"{nodes[0]}:5080"
        with c.su():
            cu.start_daemon(
                {"logfile": self.LOGS[0],
                 "pidfile": "/var/run/dgraph-zero.pid", "chdir": self.DIR},
                f"{self.DIR}/dgraph", "zero",
                "--my", f"{node}:5080",
                *( [] if i == 0 else ["--peer", peer] ),
                "--raft", f"idx={i + 1}",
                "--wal", "/var/lib/dgraph/zw",
            )
            cu.start_daemon(
                {"logfile": self.LOGS[1],
                 "pidfile": "/var/run/dgraph-alpha.pid", "chdir": self.DIR},
                f"{self.DIR}/dgraph", "alpha",
                "--my", f"{node}:7080",
                "--zero", peer,
                "--postings", "/var/lib/dgraph/p",
                "--wal", "/var/lib/dgraph/w",
                "--security", "whitelist=0.0.0.0/0",
            )

    def kill(self, test, node):
        cu.grepkill("dgraph")

    def teardown(self, test, node):
        cu.grepkill("dgraph")
        with c.su():
            c.exec("rm", "-rf", "/var/lib/dgraph")

    def log_files(self, test, node):
        return list(self.LOGS)


def upsert_checker() -> Checker:
    """Every final count must be ≤ 1 node per email; counts of 0 with an
    acked upsert are lost inserts (upsert.clj checker semantics)."""

    def chk(test, history, opts):
        acked = set()
        counts = {}
        for op in history:
            if op.f == "upsert" and op.is_ok:
                acked.add(op.value)
            elif op.f == "count" and op.is_ok:
                k, n = op.value
                counts[k] = max(counts.get(k, 0), n)
        dups = {k: n for k, n in counts.items() if n > 1}
        lost = sorted(k for k in acked if counts.get(k, 0) == 0 and counts)
        return {
            "valid": not dups and not lost,
            "acked_count": len(acked),
            "duplicates": dups,
            "lost": lost,
        }

    return checker_fn(chk, "upsert")


def upsert_workload(opts: Optional[dict] = None) -> dict:
    o = dict(opts or {})
    keys = int(o.get("keys") or 10)

    def upsert(test=None, ctx=None):
        return {"type": "invoke", "f": "upsert",
                "value": gen.rand_int(keys)}

    # A list is a generator running its elements in sequence; each
    # thread reads every email's final count.
    final = gen.clients(gen.each_thread(
        [{"type": "invoke", "f": "count", "value": k}
         for k in range(keys)]))
    return {
        "client": UpsertClient(),
        "checker": jchecker.compose({
            "upsert": upsert_checker(),
            "stats": jchecker.stats(),
        }),
        "generator": gen.clients(
            gen.limit(int(o.get("ops") or 200), upsert)),
        "final-generator": final,
    }


def set_workload(opts: Optional[dict] = None) -> dict:
    o = dict(opts or {})
    counter = [0]

    def add(test=None, ctx=None):
        counter[0] += 1
        return {"type": "invoke", "f": "add", "value": counter[0]}

    return {
        "client": SetClient(),
        "checker": jchecker.compose({
            "set": jchecker.set_checker(),
            "stats": jchecker.stats(),
        }),
        "generator": gen.clients(
            gen.limit(int(o.get("ops") or 200), add)),
        "final-generator": gen.clients(
            gen.once({"type": "invoke", "f": "read", "value": None})),
    }


def delete_checker() -> Checker:
    """Every ok read sees nothing or exactly one {uid, key} record, all
    reads agreeing on one key value (delete.clj:64-87). Runs per-key
    under independent.checker."""

    def chk(test, history, opts):
        bad = []
        keys_seen = set()
        for op in history:
            if not (op.is_ok and op.f == "read"):
                continue
            rows = op.value or []
            if len(rows) > 1:
                bad.append({"op": repr(op), "error": "multiple-records"})
                continue
            for r in rows:
                if set(r) != {"uid", "key"}:
                    bad.append({"op": repr(op), "error": "bad-record",
                                "record": r})
                else:
                    keys_seen.add(r["key"])
        if len(keys_seen) > 1:
            bad.append({"error": "cross-key-leak",
                        "keys": sorted(keys_seen)})
        return {"valid": not bad, "bad-reads": bad}

    return checker_fn(chk, "deletes")


def sequential_reg_checker() -> Checker:
    """Each process's observed register values must be monotonic
    (sequential.clj:107-140). Runs per-key under independent.checker."""

    def chk(test, history, opts):
        last: dict = {}
        errs = []
        for op in history:
            if not op.is_ok:
                continue
            v = op.value
            if not isinstance(v, int):
                continue
            p = op.process
            if v < last.get(p, 0):
                errs.append({"process": p, "from": last[p], "to": v})
            last[p] = v
        return {"valid": not errs, "non-monotonic": errs}

    return checker_fn(chk, "sequential")


def bank_workload(opts: Optional[dict] = None) -> dict:
    wl = wbank.test(dict(opts or {}))
    return {**wl, "client": BankClient(),
            "generator": gen.clients(wl["generator"])}


def delete_workload(opts: Optional[dict] = None) -> dict:
    o = dict(opts or {})

    def mop(f):
        return lambda test=None, ctx=None: {
            "type": "invoke", "f": f, "value": None}

    def fgen(k):
        return gen.stagger(0.01, gen.limit(
            int(o.get("ops-per-key") or 50),
            gen.mix([mop("read"), mop("upsert"), mop("delete")])))

    return {
        "client": DeleteClient(),
        "generator": gen.clients(independent.concurrent_generator(
            2, itertools.count(), fgen)),
        "checker": independent.checker(jchecker.compose({
            "deletes": delete_checker(),
            "stats": jchecker.stats(),
        })),
    }


def long_fork_workload(opts: Optional[dict] = None) -> dict:
    wl = wlf.workload(3)
    return {**wl, "client": TxnClient(blind_insert=True),
            "generator": gen.clients(wl["generator"])}


def register_workload(opts: Optional[dict] = None) -> dict:
    wl = wreg.test(dict(opts or {}))
    return {**wl, "client": LinRegisterClient(),
            "generator": gen.clients(
                gen.stagger(0.01, wl["generator"]))}


def sequential_workload(opts: Optional[dict] = None) -> dict:
    o = dict(opts or {})
    keys = list(range(int(o.get("keys") or 2)))

    def mop(f):
        return lambda test=None, ctx=None: {
            "type": "invoke", "f": f, "value": None}

    def fgen(k):
        return gen.stagger(0.01, gen.mix([mop("inc"), mop("read")]))

    return {
        "client": SequentialRegClient(),
        "generator": gen.clients(independent.concurrent_generator(
            2, keys, fgen)),
        "checker": independent.checker(jchecker.compose({
            "sequential": sequential_reg_checker(),
            "stats": jchecker.stats(),
        })),
    }


def wr_workload(opts: Optional[dict] = None) -> dict:
    o = dict(opts or {})
    wl = wwr.test({
        "key_count": 4,
        "min_txn_length": 2,
        "max_txn_length": 4,
        "max_writes_per_key": 16,
        # wr.clj:22-31: wfr + sequential version orders + the realtime
        # graph (dgraph claims linearizability) — strict serializability.
        "wfr_keys": True,
        "sequential_keys": True,
        "additional_graphs": ["realtime"],
        "anomalies": ["G0", "G1c", "G-single", "G1a", "G1b", "internal"],
    })
    return {
        "client": TxnClient(),
        "generator": gen.clients(
            gen.limit(int(o.get("ops") or 200), wl["generator"])),
        "checker": jchecker.compose({
            "wr": wl["checker"],
            "stats": jchecker.stats(),
        }),
    }


WORKLOADS = {
    "upsert": upsert_workload,
    "set": set_workload,
    "bank": bank_workload,
    "delete": delete_workload,
    "long-fork": long_fork_workload,
    "linearizable-register": register_workload,
    "sequential": sequential_workload,
    "wr": wr_workload,
}


def trace_export_checker(collector) -> Checker:
    """Writes spans.jsonl into the store directory at analysis time (the
    same store-side-effect seam timeline.html uses)."""

    def chk(test, history, opts):
        path = jtrace.store_spans(test, collector)
        return {"valid": True, "spans": len(collector.spans),
                "file": path}

    return checker_fn(chk, "trace")


def test_fn(opts: dict) -> dict:
    name = opts.get("workload") or "upsert"
    wl = WORKLOADS[name](opts)
    client = wl["client"]
    checker = wl["checker"]
    if opts.get("trace"):
        collector = jtrace.Collector()
        client = jtrace.tracing(client, collector)
        checker = jchecker.compose({
            "workload": checker,
            "trace": trace_export_checker(collector),
        })
    return {
        "name": f"dgraph-{name}",
        "db": DgraphDB(),
        "net": jnet.iptables(),
        "nemesis": jnemesis.partition_random_halves(),
        **{k: v for k, v in wl.items()
           if k not in ("generator", "final-generator", "client",
                        "checker")},
        "client": client,
        "checker": checker,
        "generator": std_generator(
            opts, wl["generator"],
            final_client_gen=wl.get("final-generator")),
    }


def _add_opts(p):
    p.add_argument("--workload", choices=sorted(WORKLOADS),
                   default="upsert")
    p.add_argument("--ops", type=int, default=200)
    p.add_argument("--keys", type=int, default=10)
    p.add_argument("--trace", action="store_true")


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn, add_opts=_add_opts), argv)


if __name__ == "__main__":
    main()
