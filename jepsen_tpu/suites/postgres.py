"""PostgreSQL (stolon-style) list-append suite.

Mirrors the reference stolon suite's elle append test (stolon/src/...,
SURVEY §2.6): transactions over rows of a table, driven through ``psql``
on the node via the control session — no client driver dependency, the
same trick the reference uses for CLI-driven databases. Each txn runs as
one serializable SQL transaction; serialization failures map to :fail
(definite) and connection errors to indeterminate.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from .. import cli, client as jclient, db as jdb, generator as gen
from .. import nemesis as jnemesis, net as jnet
from ..control import util as cu
from ..workloads import append as wa
from .. import control as c
from . import std_generator

TABLE = "jepsen_append"


class PsqlClient(jclient.Client):
    """Runs each txn as a single psql serializable transaction on the
    node. Requires the test's sessions (control plane) — the client rides
    the same transport as DB setup."""

    def __init__(self, node: Any = None, user: str = "postgres",
                 host: Optional[str] = None, port: Optional[int] = None):
        # host/port target an in-node proxy (e.g. stolon-proxy); None =
        # the local Unix socket (plain postgres).
        self.node = node
        self.user = user
        self.host = host
        self.port = port

    def open(self, test, node):
        return type(self)(node, self.user, self.host, self.port)

    def setup(self, test):
        self._psql(test,
                   f"CREATE TABLE IF NOT EXISTS {TABLE} "
                   "(k text PRIMARY KEY, v jsonb NOT NULL)")

    def _psql(self, test, sql: str) -> str:
        # psql -c prints only the LAST command's result; feeding the
        # script on stdin prints every statement's output.
        def run(t, node):
            return c.exec_star(
                f"psql -U {c.escape(self.user)} -At "
                + (f"-h {c.escape(self.host)} " if self.host else "")
                + (f"-p {self.port} " if self.port else "")
                + f"-v ON_ERROR_STOP=1 <<'JEPSEN_SQL'\n"
                f"{sql}\nJEPSEN_SQL")

        return c.on_nodes(test, run, [self.node])[self.node]

    def invoke(self, test, op):
        stmts = ["BEGIN ISOLATION LEVEL SERIALIZABLE"]
        reads = []
        for i, (f, k, v) in enumerate(op["value"]):
            if f == "r":
                reads.append(i)
                stmts.append(
                    f"SELECT COALESCE((SELECT v FROM {TABLE} "
                    f"WHERE k = '{k}'), '[]'::jsonb)")
            else:
                stmts.append(
                    f"INSERT INTO {TABLE} (k, v) VALUES ('{k}', "
                    f"'[{v}]'::jsonb) ON CONFLICT (k) DO UPDATE SET "
                    f"v = {TABLE}.v || '{v}'::jsonb")
        stmts.append("COMMIT")
        sql = ";\n".join(stmts) + ";"
        try:
            out = self._psql(test, sql)
        except c.RemoteError as e:
            if "could not serialize" in str(e) or "deadlock" in str(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise  # indeterminate
        lines = [l for l in out.split("\n") if l.strip()]
        done = []
        ri = 0
        for f, k, v in op["value"]:
            if f == "r":
                done.append([f, k, json.loads(lines[ri])])
                ri += 1
            else:
                done.append([f, k, v])
        return {**op, "type": "ok", "value": done}


class PostgresDB(jdb.DB, jdb.Process, jdb.LogFiles):
    LOG = "/var/log/postgresql-jepsen.log"

    def setup(self, test, node):
        from ..os_ import debian

        debian.install(["postgresql"])
        self.start(test, node)
        with c.su():
            c.exec_star(
                "su postgres -c \"psql -c \\\"ALTER SYSTEM SET "
                "listen_addresses = '*'\\\"\" || true")

    def start(self, test, node):
        with c.su():
            c.exec_star("service postgresql start || pg_ctlcluster "
                        "$(ls /var/lib/postgresql | head -1) main start")

    def kill(self, test, node):
        cu.grepkill("postgres")

    def teardown(self, test, node):
        with c.su():
            c.exec_star(
                f"su postgres -c \"psql -c 'DROP TABLE IF EXISTS {TABLE}'\""
                " || true")

    def log_files(self, test, node):
        return [self.LOG]


BANK_TABLE = "jepsen_bank"


class PgBankClient(PsqlClient):
    """Bank transfers in serializable psql transactions
    (postgres_rds.clj:133-260's BankClient shape): reads select every
    balance, transfers run two guarded UPDATEs in one txn;
    serialization failures are definite :fail."""

    def setup(self, test):
        from ..workloads import bank as wbank

        rows = ", ".join(
            f"({a}, {b})" for a, b in wbank.initial_balances(test))
        self._psql(test,
                   f"CREATE TABLE IF NOT EXISTS {BANK_TABLE} "
                   "(id int PRIMARY KEY, "
                   "balance bigint NOT NULL CHECK (balance >= 0));\n"
                   f"INSERT INTO {BANK_TABLE} VALUES {rows} "
                   "ON CONFLICT (id) DO NOTHING")

    def invoke(self, test, op):
        if op["f"] == "read":
            out = self._psql(
                test, f"SELECT id, balance FROM {BANK_TABLE}")
            value = {}
            for line in out.split("\n"):
                if "|" in line:
                    a, b = line.split("|")[:2]
                    value[int(a)] = int(b)
            return {**op, "type": "ok", "value": value}
        v = op["value"]
        try:
            self._psql(test, ";\n".join([
                "BEGIN ISOLATION LEVEL SERIALIZABLE",
                f"UPDATE {BANK_TABLE} SET balance = balance - "
                f"{v['amount']} WHERE id = {v['from']}",
                f"UPDATE {BANK_TABLE} SET balance = balance + "
                f"{v['amount']} WHERE id = {v['to']}",
                "COMMIT",
            ]) + ";")
            return {**op, "type": "ok"}
        except c.RemoteError as e:
            s = str(e)
            if "could not serialize" in s or "deadlock" in s \
                    or "violates check constraint" in s:
                return {**op, "type": "fail", "error": "serialization"}
            raise


def append_workload(opts: dict) -> dict:
    wl = wa.test({"key_count": 4})
    return {"client": PsqlClient(), "checker": wl["checker"],
            "generator": wl["generator"]}


def bank_workload(opts: dict) -> dict:
    from ..workloads import bank as wbank

    wl = wbank.test(opts)
    return {**wl, "client": PgBankClient()}


WORKLOADS = {"append": append_workload, "bank": bank_workload}


def test_fn(opts: dict) -> dict:
    name = opts.get("workload") or "append"
    wl = WORKLOADS[name](opts)
    return {
        "name": f"postgres-{name}",
        "db": PostgresDB(),
        "net": jnet.iptables(),
        "nemesis": jnemesis.partition_random_halves(),
        **{k: v for k, v in wl.items() if k != "generator"},
        "generator": std_generator(opts, wl["generator"], dt=10),
    }


def _add_opts(p):
    p.add_argument("--workload", choices=sorted(WORKLOADS),
                   default="append")


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn, add_opts=_add_opts), argv)


if __name__ == "__main__":
    main()
