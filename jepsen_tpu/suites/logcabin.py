"""LogCabin suite: CAS register through the TreeOps CLI over control.

The reference (logcabin/src/jepsen/logcabin.clj, 300 LoC) is the one
suite whose client is a REMOTE CLI, not a wire protocol: every
read/write/cas runs the ``TreeOps`` binary on a node through the
control layer, with CAS failure detected by matching LogCabin's
exception text (logcabin.clj:140-209). The DB builds LogCabin from
source with scons, bootstraps the Raft cluster on the first node, and
grows it with the ``Reconfigure`` tool (logcabin.clj:23-160).

This port keeps that exact shape: the client invokes TreeOps via
``control.exec`` on its session's node (so the whole control/session
machinery is the transport), values ride JSON like the reference, and
the verdict comes from the standard linearizable register dispatch.
"""

from __future__ import annotations

import json
import re
from typing import Any, Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from .. import models as jmodels
from .. import nemesis as jnemesis, net as jnet
from ..control import util as cu
from .. import control as c
from . import std_generator

CONFIG = "/root/logcabin.conf"
LOG = "/root/logcabin.log"
PID = "/root/logcabin.pid"
STORE = "/root/storage"
BIN = "/root/LogCabin"
RECONFIGURE = "/root/Reconfigure"
TREEOPS = "/root/TreeOps"
KEY = "/jepsen"
OP_TIMEOUT = 3

CAS_MSG = re.compile(
    r"Exiting due to LogCabin::Client::Exception: Path '.*' has value "
    r"'.*', not '.*' as required")
TIMEOUT_MSG = re.compile(
    r"Exiting due to LogCabin::Client::Exception: Client-specified "
    r"timeout elapsed")


def server_addrs(test: dict) -> str:
    return ",".join(f"{n}:5254" for n in test["nodes"])


class CasClient(jclient.Client):
    """read/write/cas on one tree path via TreeOps
    (logcabin.clj:163-243). Like the reference's ``(c/on node …)``,
    each call binds the node's control session — the interpreter's
    worker threads have no ambient binding."""

    def __init__(self, node: Any = None):
        self.node = node

    def open(self, test, node):
        return CasClient(node)

    def _bound(self, test):
        session = (test.get("sessions") or {}).get(self.node)
        if session is None:
            raise RuntimeError(f"no control session for {self.node!r}")
        return c.with_session(self.node, session)

    def setup(self, test):
        with self._bound(test):
            c.exec_star(
                f"echo -n {c.escape(json.dumps(None))} | {TREEOPS} "
                f"-c {server_addrs(test)} -q -t {OP_TIMEOUT} write {KEY}")

    def invoke(self, test, op):
        with self._bound(test):
            return self._invoke(test, op)

    def _invoke(self, test, op):
        addrs = server_addrs(test)
        try:
            if op["f"] == "read":
                out = c.exec_star(
                    f"{TREEOPS} -c {addrs} -q -t {OP_TIMEOUT} read {KEY}")
                return {**op, "type": "ok", "value": json.loads(out)}
            if op["f"] == "write":
                v = json.dumps(op["value"])
                c.exec_star(
                    f"echo -n {c.escape(v)} | {TREEOPS} -c {addrs} -q "
                    f"-t {OP_TIMEOUT} write {KEY}")
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = op["value"]
                o, n = json.dumps(old), json.dumps(new)
                try:
                    c.exec_star(
                        f"echo -n {c.escape(n)} | {TREEOPS} -c {addrs} "
                        f"-q -p {c.escape(KEY + ':' + o)} "
                        f"-t {OP_TIMEOUT} write {KEY}")
                except c.RemoteError as e:
                    if CAS_MSG.search(str(e)):
                        return {**op, "type": "fail"}
                    raise
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except c.RemoteError as e:
            if TIMEOUT_MSG.search(str(e)):
                # Reads are idempotent; mutations may have landed.
                t = "fail" if op["f"] == "read" else "info"
                return {**op, "type": t, "error": "timed-out"}
            raise

    def close(self, test):
        pass


class LogCabinDB(jdb.DB, jdb.Process, jdb.Primary, jdb.LogFiles):
    """scons build + bootstrap-on-n1 + Reconfigure grow
    (logcabin.clj:23-160). The cluster-grow runs via the Primary hook
    — AFTER every node's setup completes (db.cycle runs setups in
    parallel; the reference synchronizes before reconfiguring,
    logcabin.clj:140-146)."""

    def setup(self, test, node):
        from ..os_ import debian

        debian.install(["git-core", "protobuf-compiler",
                        "libprotobuf-dev", "libcrypto++-dev", "g++",
                        "scons"])
        with c.su():
            c.exec_star(
                "[ -d /logcabin ] || git clone --depth 1 "
                "https://github.com/logcabin/logcabin.git /logcabin")
            c.exec_star("cd /logcabin && git submodule update --init "
                        "&& scons")
            for src, dst in (("build/LogCabin", BIN),
                             ("build/Examples/Reconfigure", RECONFIGURE),
                             ("build/Examples/TreeOps", TREEOPS)):
                c.exec("cp", "-f", f"/logcabin/{src}", dst)
        # Positional server ids: hostname-derived ids collide for
        # digit-free or same-numbered names ("db1.east"/"db1.west").
        sid = test["nodes"].index(node) + 1
        conf = f"serverId = {sid}\nlistenAddresses = {node}:5254\n"
        with c.su():
            c.exec_star(f"echo {c.escape(conf)} > {CONFIG}")
            c.exec("rm", "-rf", LOG)
            if node == test["nodes"][0]:
                c.exec_star(f"cd /root && {BIN} -c {CONFIG} -l {LOG} "
                            f"--bootstrap")
        self.start(test, node)

    def setup_primary(self, test, node):
        with c.su():
            addrs = " ".join(f"{n}:5254" for n in test["nodes"])
            c.exec_star(
                f"cd /root && {RECONFIGURE} -c "
                f"{server_addrs(test)} set {addrs}")

    def start(self, test, node):
        with c.su():
            c.exec_star(f"cd /root && {BIN} -c {CONFIG} -d -l {LOG} "
                        f"-p {PID}")

    def kill(self, test, node):
        cu.grepkill("LogCabin")

    def teardown(self, test, node):
        cu.grepkill("LogCabin")
        with c.su():
            c.exec("rm", "-rf", STORE, PID)

    def log_files(self, test, node):
        return [LOG]


def cas_workload(opts: Optional[dict] = None) -> dict:
    o = dict(opts or {})

    def w(test=None, ctx=None):
        return {"type": "invoke", "f": "write",
                "value": gen.rand_int(5)}

    def r(test=None, ctx=None):
        return {"type": "invoke", "f": "read", "value": None}

    def cas(test=None, ctx=None):
        return {"type": "invoke", "f": "cas",
                "value": [gen.rand_int(5), gen.rand_int(5)]}

    return {
        "client": CasClient(),
        "checker": jchecker.compose({
            "linear": jchecker.linearizable(
                model=jmodels.CasRegister(init=None)),
            "stats": jchecker.stats(),
        }),
        "generator": gen.clients(gen.limit(
            int(o.get("ops") or 200), gen.mix([w, r, cas]))),
    }


WORKLOADS = {"cas": cas_workload}


def test_fn(opts: dict) -> dict:
    wl = cas_workload(opts)
    test = {
        "name": "logcabin-cas",
        "db": LogCabinDB(),
        "net": jnet.iptables(),
        "nemesis": jnemesis.partition_random_halves(),
        **{k: v for k, v in wl.items() if k != "generator"},
    }
    test["generator"] = std_generator(opts, wl["generator"])
    return test


def _add_opts(p):
    p.add_argument("--ops", type=int, default=200)


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn, add_opts=_add_opts), argv)


if __name__ == "__main__":
    main()
