"""CockroachDB suite: bank + list-append txns over the pg wire via the
node's ``cockroach sql`` shell.

Mirrors the reference cockroachdb suite (cockroachdb/src/jepsen/
cockroach/*.clj, 2515 LoC): register/bank/append workloads, a rich
composed nemesis including its own clock-skew C tooling (here the shared
jepsen_tpu.nemesis.time tools serve), and the serializable-SQL client
discipline — serialization failures are definite :fail, connection drops
indeterminate.
"""

from __future__ import annotations

import json
from typing import Any

from .. import cli, client as jclient, db as jdb, generator as gen
from .. import net as jnet
from ..control import util as cu
from ..nemesis import combined as ncombined
from ..workloads import append as wa
from ..workloads import bank as wbank
from .. import control as c

BANK_TABLE = "jepsen_bank"
APPEND_TABLE = "jepsen_append"


class _SqlClient(jclient.Client):
    """Runs SQL via `cockroach sql` on the node (the CLI analogue of the
    reference's JDBC client)."""

    def __init__(self, node: Any = None):
        self.node = node

    def open(self, test, node):
        return type(self)(node)

    def _sql(self, test, script: str) -> str:
        def run(t, node):
            return c.exec_star(
                "/opt/cockroach/cockroach sql --insecure --format=tsv "
                f"<<'JEPSEN_SQL'\n{script}\nJEPSEN_SQL")

        return c.on_nodes(test, run, [self.node])[self.node]


class BankClient(_SqlClient):
    """Transfers inside one serializable txn; reads select all balances
    (cockroach/bank.clj semantics)."""

    def setup(self, test):
        rows = ", ".join(
            f"({a}, {b})" for a, b in wbank.initial_balances(test))
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {BANK_TABLE} "
                  "(id INT PRIMARY KEY, balance INT NOT NULL CHECK (balance >= 0));\n"
                  f"UPSERT INTO {BANK_TABLE} VALUES {rows};")

    def invoke(self, test, op):
        if op["f"] == "read":
            out = self._sql(test, f"SELECT id, balance FROM {BANK_TABLE};")
            lines = [l.split("\t") for l in out.strip().split("\n")[1:] if l]
            value = {int(i): int(b) for i, b in lines}
            return {**op, "type": "ok", "value": value}
        v = op["value"]
        try:
            self._sql(test, "\n".join([
                "BEGIN;",
                f"UPDATE {BANK_TABLE} SET balance = balance - {v['amount']} "
                f"WHERE id = {v['from']};",
                f"UPDATE {BANK_TABLE} SET balance = balance + {v['amount']} "
                f"WHERE id = {v['to']};",
                "COMMIT;",
            ]))
            return {**op, "type": "ok"}
        except c.RemoteError as e:
            s = str(e).lower()
            if "restart transaction" in s or "retry" in s or "constraint" in s:
                return {**op, "type": "fail", "error": "serialization"}
            raise


class AppendClient(_SqlClient):
    """List-append via jsonb rows in one serializable txn (the reference's
    ysql/append pattern)."""

    def setup(self, test):
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {APPEND_TABLE} "
                  "(k STRING PRIMARY KEY, v JSONB NOT NULL);")

    def invoke(self, test, op):
        stmts = ["BEGIN;"]
        for f, k, v in op["value"]:
            if f == "r":
                stmts.append(
                    f"SELECT COALESCE((SELECT v FROM {APPEND_TABLE} "
                    f"WHERE k = '{k}'), '[]'::JSONB);")
            else:
                stmts.append(
                    f"INSERT INTO {APPEND_TABLE} VALUES ('{k}', "
                    f"'[{v}]'::JSONB) ON CONFLICT (k) DO UPDATE SET "
                    f"v = {APPEND_TABLE}.v || '{v}'::JSONB;")
        stmts.append("COMMIT;")
        try:
            out = self._sql(test, "\n".join(stmts))
        except c.RemoteError as e:
            if "restart transaction" in str(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise
        # Non-interactive `cockroach sql` prints statement tags (BEGIN,
        # INSERT ...) and column headers; only JSON-array lines are read
        # results.
        lines = [l for l in out.strip().split("\n")
                 if l.strip().startswith("[")]
        done = []
        ri = 0
        for f, k, v in op["value"]:
            if f == "r":
                done.append([f, k, json.loads(lines[ri])])
                ri += 1
            else:
                done.append([f, k, v])
        return {**op, "type": "ok", "value": done}


class CockroachDB(jdb.DB, jdb.Process, jdb.LogFiles):
    DIR = "/opt/cockroach"
    LOG = "/var/log/cockroach.log"
    PID = "/var/run/cockroach.pid"

    def __init__(self, version: str = "23.1.11"):
        self.version = version

    def setup(self, test, node):
        url = (f"https://binaries.cockroachdb.com/"
               f"cockroach-v{self.version}.linux-amd64.tgz")
        cu.install_archive(url, self.DIR)
        self.start(test, node)

    def start(self, test, node):
        joins = ",".join(test["nodes"])
        with c.su():
            cu.start_daemon(
                {"logfile": self.LOG, "pidfile": self.PID, "chdir": self.DIR},
                f"{self.DIR}/cockroach",
                "start", "--insecure",
                "--advertise-addr", node,
                "--join", joins,
                "--store", "/var/lib/cockroach",
            )
        if node == test["nodes"][0]:
            try:
                c.exec_star(
                    f"{self.DIR}/cockroach init --insecure --host={node}")
            except c.RemoteError as e:
                # Re-init of an initialized cluster is expected; anything
                # else should be visible in the logs.
                if "already" not in str(e):
                    import logging

                    logging.getLogger("jepsen.cockroachdb").warning(
                        "cockroach init failed: %s", e)

    def kill(self, test, node):
        cu.grepkill("cockroach")

    def teardown(self, test, node):
        cu.grepkill("cockroach")
        with c.su():
            c.exec("rm", "-rf", "/var/lib/cockroach", self.PID)

    def log_files(self, test, node):
        return [self.LOG]


def bank_workload(opts: dict) -> dict:
    wl = wbank.test(opts)
    return {**wl, "client": BankClient()}


def append_workload(opts: dict) -> dict:
    wl = wa.test({"key_count": 4})
    return {"client": AppendClient(), "generator": wl["generator"],
            "checker": wl["checker"]}


WORKLOADS = {"bank": bank_workload, "append": append_workload}


def test_fn(opts: dict) -> dict:
    name = opts.get("workload") or "bank"
    wl = WORKLOADS[name](opts)
    db = CockroachDB(str(opts.get("version") or "23.1.11"))
    pkg = ncombined.nemesis_package({
        "db": db,
        "interval": opts.get("nemesis_interval") or 10,
        "faults": (opts.get("faults") or "partition,kill").split(","),
    })
    test = {
        "name": f"cockroachdb-{name}",
        "db": db,
        "net": jnet.iptables(),
        "nemesis": pkg["nemesis"],
        "plot": {"nemeses": pkg["perf"]},
        **{k: v for k, v in wl.items() if k != "generator"},
    }
    # Time-limit the WHOLE nemesis+client composite: nemesis-package
    # generators repeat on an interval forever and would otherwise keep
    # the phase alive after the client generator exhausts.
    test["generator"] = gen.phases(
        gen.time_limit(
            opts.get("time_limit", 60),
            gen.nemesis(pkg["generator"], wl["generator"])),
        gen.nemesis(pkg["final-generator"]),
    )
    return test


def _add_opts(p):
    p.add_argument("--workload", choices=sorted(WORKLOADS), default="bank")
    p.add_argument("--version", default="23.1.11")
    p.add_argument("--faults", default="partition,kill")
    p.add_argument("--nemesis-interval", type=int, default=10)


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn, add_opts=_add_opts), argv)


if __name__ == "__main__":
    main()
