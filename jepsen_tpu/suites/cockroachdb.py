"""CockroachDB suite over the pg wire via the node's ``cockroach sql``
shell.

Mirrors the reference cockroachdb suite (cockroachdb/src/jepsen/
cockroach/*.clj, 2515 LoC) with its full workload roster — register
(register.clj), bank (bank.clj), sets (sets.clj), monotonic
(monotonic.clj), sequential (sequential.clj), comments (comments.clj),
g2/adya (adya.clj), append — a rich composed nemesis including its own
clock-skew C tooling (here the shared jepsen_tpu.nemesis.time tools
serve), and the serializable-SQL client discipline — serialization
failures are definite :fail, connection drops indeterminate.

Where the reference's clients branch on mid-transaction query results
(monotonic's max+1 insert, adya's read-then-insert), these clients
collapse the logic into single INSERT…SELECT / WHERE NOT EXISTS
statements — atomic under serializable isolation and shippable through
a one-shot SQL shell.
"""

from __future__ import annotations

import itertools
import json
import threading
import zlib
from collections import Counter, deque
from decimal import Decimal
from typing import Any, Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from .. import independent
from .. import net as jnet
from ..control import util as cu
from ..nemesis import combined as ncombined
from ..workloads import adya as wadya
from ..workloads import append as wa
from ..workloads import bank as wbank
from ..workloads import linearizable_register as wreg
from .. import control as c

BANK_TABLE = "jepsen_bank"
APPEND_TABLE = "jepsen_append"
REGISTER_TABLE = "jepsen_register"
SET_TABLE = "jepsen_set"
SEQ_TABLES = 10
SEQ_PREFIX = "jepsen_seq_"
COMMENT_TABLES = 10
COMMENT_PREFIX = "jepsen_comment_"
G2_PREFIX = "jepsen_g2_"


class _SqlClient(jclient.Client):
    """Runs SQL via `cockroach sql` on the node (the CLI analogue of the
    reference's JDBC client)."""

    def __init__(self, node: Any = None):
        self.node = node

    def open(self, test, node):
        return type(self)(node)

    def _sql(self, test, script: str) -> str:
        def run(t, node):
            return c.exec_star(
                "/opt/cockroach/cockroach sql --insecure --format=tsv "
                f"<<'JEPSEN_SQL'\n{script}\nJEPSEN_SQL")

        return c.on_nodes(test, run, [self.node])[self.node]


class BankClient(_SqlClient):
    """Transfers inside one serializable txn; reads select all balances
    (cockroach/bank.clj semantics)."""

    def setup(self, test):
        rows = ", ".join(
            f"({a}, {b})" for a, b in wbank.initial_balances(test))
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {BANK_TABLE} "
                  "(id INT PRIMARY KEY, balance INT NOT NULL CHECK (balance >= 0));\n"
                  f"UPSERT INTO {BANK_TABLE} VALUES {rows};")

    def invoke(self, test, op):
        if op["f"] == "read":
            out = self._sql(test, f"SELECT id, balance FROM {BANK_TABLE};")
            lines = [l.split("\t") for l in out.strip().split("\n")[1:] if l]
            value = {int(i): int(b) for i, b in lines}
            return {**op, "type": "ok", "value": value}
        v = op["value"]
        try:
            self._sql(test, "\n".join([
                "BEGIN;",
                f"UPDATE {BANK_TABLE} SET balance = balance - {v['amount']} "
                f"WHERE id = {v['from']};",
                f"UPDATE {BANK_TABLE} SET balance = balance + {v['amount']} "
                f"WHERE id = {v['to']};",
                "COMMIT;",
            ]))
            return {**op, "type": "ok"}
        except c.RemoteError as e:
            s = str(e).lower()
            if "restart transaction" in s or "retry" in s or "constraint" in s:
                return {**op, "type": "fail", "error": "serialization"}
            raise


class AppendClient(_SqlClient):
    """List-append via jsonb rows in one serializable txn (the reference's
    ysql/append pattern)."""

    def setup(self, test):
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {APPEND_TABLE} "
                  "(k STRING PRIMARY KEY, v JSONB NOT NULL);")

    def invoke(self, test, op):
        stmts = ["BEGIN;"]
        for f, k, v in op["value"]:
            if f == "r":
                stmts.append(
                    f"SELECT COALESCE((SELECT v FROM {APPEND_TABLE} "
                    f"WHERE k = '{k}'), '[]'::JSONB);")
            else:
                stmts.append(
                    f"INSERT INTO {APPEND_TABLE} VALUES ('{k}', "
                    f"'[{v}]'::JSONB) ON CONFLICT (k) DO UPDATE SET "
                    f"v = {APPEND_TABLE}.v || '{v}'::JSONB;")
        stmts.append("COMMIT;")
        try:
            out = self._sql(test, "\n".join(stmts))
        except c.RemoteError as e:
            if "restart transaction" in str(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise
        # Non-interactive `cockroach sql` prints statement tags (BEGIN,
        # INSERT ...) and column headers; only JSON-array lines are read
        # results.
        lines = [l for l in out.strip().split("\n")
                 if l.strip().startswith("[")]
        done = []
        ri = 0
        for f, k, v in op["value"]:
            if f == "r":
                done.append([f, k, json.loads(lines[ri])])
                ri += 1
            else:
                done.append([f, k, v])
        return {**op, "type": "ok", "value": done}


def _tsv_rows(out: str, fields: Optional[int] = None) -> list[list[str]]:
    """Data rows of `cockroach sql --format=tsv` output: tab-split lines
    with ``fields`` columns (any width if None) whose first column isn't
    a statement tag / header word."""
    rows = []
    for line in out.strip().split("\n"):
        cells = line.rstrip("\n").split("\t")
        if fields is not None and len(cells) != fields:
            continue
        head = cells[0].strip()
        if not head or not (head.lstrip("-").replace(".", "", 1).isdigit()):
            continue
        rows.append([cell.strip() for cell in cells])
    return rows


def _is_serialization_error(e: Exception) -> bool:
    # Match cockroach's retryable-txn error text only; the RemoteError
    # message embeds the whole command + stdout/stderr, so a looser
    # match (e.g. bare "retry") could turn an indeterminate outcome
    # into a false definite :fail.
    return "restart transaction" in str(e).lower()


class RegisterClient(_SqlClient):
    """Keyed cas-register, one row per independent key
    (cockroach/register.clj:18-77). cas decides by RETURNING-row
    presence — no rowcount parsing needed through the SQL shell."""

    def setup(self, test):
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {REGISTER_TABLE} "
                  "(id INT PRIMARY KEY, val INT);")

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "read":
                out = self._sql(
                    test,
                    f"SELECT val FROM {REGISTER_TABLE} WHERE id = {k};")
                rows = _tsv_rows(out, 1)
                val = int(rows[0][0]) if rows else None
                return {**op, "type": "ok",
                        "value": independent.tuple_(k, val)}
            if op["f"] == "write":
                self._sql(test,
                          f"UPSERT INTO {REGISTER_TABLE} VALUES ({k}, {v});")
                return {**op, "type": "ok"}
            old, new = v
            out = self._sql(
                test,
                f"UPDATE {REGISTER_TABLE} SET val = {new} "
                f"WHERE id = {k} AND val = {old} RETURNING id;")
            return {**op, "type": "ok" if _tsv_rows(out, 1) else "fail"}
        except c.RemoteError as e:
            if _is_serialization_error(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise


class SetsClient(_SqlClient):
    """Blind unique-int inserts + full reads (cockroach/sets.clj)."""

    def setup(self, test):
        self._sql(test,
                  f"CREATE TABLE IF NOT EXISTS {SET_TABLE} (val INT);")

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                out = self._sql(test, f"SELECT val FROM {SET_TABLE};")
                return {**op, "type": "ok",
                        "value": [int(r[0]) for r in _tsv_rows(out, 1)]}
            self._sql(
                test, f"INSERT INTO {SET_TABLE} VALUES ({op['value']});")
            return {**op, "type": "ok"}
        except c.RemoteError as e:
            if _is_serialization_error(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise


# --- monotonic (cockroach/monotonic.clj) -----------------------------------


def _mono_table(k, i: int) -> str:
    return f"jepsen_mono_k{k}i{i}"


class MonotonicClient(_SqlClient):
    """Monotonic inserts over two tables per independent key
    (monotonic.clj:30-140). The reference reads max(val) then inserts
    max+1 inside a txn; here one INSERT…SELECT GREATEST(…)+1 does both
    atomically, with sts = cluster_logical_timestamp()."""

    TABLES = 2

    def __init__(self, node: Any = None, keys=(0, 1)):
        super().__init__(node)
        self.keys = tuple(keys)

    def open(self, test, node):
        return type(self)(node, self.keys)

    def setup(self, test):
        self._sql(test, "\n".join(
            f"CREATE TABLE IF NOT EXISTS {_mono_table(k, i)} "
            "(val INT, sts STRING, node INT, process INT, tb INT);"
            for k in self.keys for i in range(self.TABLES)))

    def invoke(self, test, op):
        k, _v = op["value"]
        tables = [_mono_table(k, i) for i in range(self.TABLES)]
        maxes = ", ".join(
            f"(SELECT COALESCE(MAX(val), 0) FROM {t})" for t in tables)
        try:
            if op["f"] == "add":
                tb = gen.rand_int(self.TABLES)
                nodes = list(test.get("nodes") or [])
                node_num = nodes.index(self.node) if self.node in nodes else 0
                proc = op.get("process")
                proc = proc if isinstance(proc, int) else 0
                out = self._sql(
                    test,
                    f"INSERT INTO {tables[tb]} (val, sts, node, process, tb) "
                    f"SELECT GREATEST({maxes}) + 1, "
                    "cluster_logical_timestamp()::STRING, "
                    f"{node_num}, {proc}, {tb} RETURNING val, sts;")
                rows = _tsv_rows(out, 2)
                row = {"val": int(rows[0][0]), "sts": rows[0][1],
                       "node": node_num, "process": proc, "tb": tb}
                return {**op, "type": "ok",
                        "value": independent.tuple_(k, row)}
            out = self._sql(test, "\n".join(
                f"SELECT val, sts, node, process, tb FROM {t};"
                for t in tables))
            rows = [
                {"val": int(r[0]), "sts": r[1], "node": int(r[2]),
                 "process": int(r[3]), "tb": int(r[4])}
                for r in _tsv_rows(out, 5)
            ]
            rows.sort(key=lambda r: Decimal(r["sts"]))
            return {**op, "type": "ok", "value": independent.tuple_(k, rows)}
        except c.RemoteError as e:
            if _is_serialization_error(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise


def _non_monotonic(ok, key, rows):
    """Successive pairs where ``ok(prev, cur)`` does NOT hold
    (monotonic.clj:150-158)."""
    return [
        [a, b] for a, b in zip(rows, rows[1:]) if not ok(key(a), key(b))
    ]


def check_monotonic(global_: bool = True) -> jchecker.Checker:
    """Timestamps and values proceed monotonically; lost / duplicate /
    revived elements are failures (monotonic.clj:160-233). Runs per-key
    under independent.checker. The reference's extra :linearizable flag
    only re-enables the global value-order check when global? is false
    (the multitable configuration, monotonic.clj:236-268); with
    global_=True it is subsumed, so it isn't reproduced here."""

    def chk(test, history, opts):
        adds = [op.value for op in history if op.is_ok and op.f == "add"]
        final = None
        for op in history:
            if op.is_ok and op.f == "read":
                final = op.value
        if final is None:
            return {"valid": "unknown", "error": "Set was never read"}
        # The client returns rows sorted by the decimal HLC timestamp,
        # so the interesting invariant is val-vs-sts agreement: in sts
        # order, vals must be strictly increasing (a later max+1 insert
        # must carry a later timestamp). The reference's separate
        # off-order-stss check is vacuous there too (its client also
        # sorts by sts, monotonic.clj:127-130) and isn't reproduced.
        off_vals = _non_monotonic(
            lambda a, b: a < b, lambda r: r["val"], final)
        by_proc: dict = {}
        for r in final:
            by_proc.setdefault(r["process"], []).append(r)
        off_per_proc = {
            p: _non_monotonic(lambda a, b: a < b, lambda r: r["val"], rs)
            for p, rs in by_proc.items()
        }
        add_vals = {r["val"] for r in adds}
        read_vals = [r["val"] for r in final]
        dups = sorted(v for v, n in Counter(read_vals).items() if n > 1)
        lost = sorted(add_vals - set(read_vals))
        return {
            "valid": not (lost or dups
                          or (global_ and off_vals)
                          or any(off_per_proc.values())),
            "lost": lost,
            "duplicates": dups,
            "value-reorders": off_vals,
            "value-reorders-per-process": {
                p: v for p, v in off_per_proc.items() if v},
        }

    return jchecker.checker_fn(chk, "monotonic")


# --- sequential (cockroach/sequential.clj) ---------------------------------


def _seq_table(subkey: str) -> str:
    return f"{SEQ_PREFIX}{zlib.crc32(subkey.encode()) % SEQ_TABLES}"


def _subkeys(key_count: int, k) -> list[str]:
    return [f"{k}_{i}" for i in range(key_count)]


class SequentialClient(_SqlClient):
    """Per-process key chains across sharded tables
    (sequential.clj:34-107): writes insert subkeys in order, each its
    own transaction; reads probe them in reverse."""

    def __init__(self, node: Any = None, key_count: int = 5):
        super().__init__(node)
        self.key_count = key_count

    def open(self, test, node):
        return type(self)(node, self.key_count)

    def setup(self, test):
        self._sql(test, "\n".join(
            f"CREATE TABLE IF NOT EXISTS {SEQ_PREFIX}{i} "
            "(key STRING PRIMARY KEY);" for i in range(SEQ_TABLES)))

    def invoke(self, test, op):
        ks = _subkeys(self.key_count, op["value"])
        try:
            if op["f"] == "write":
                # One round-trip; each INSERT is still its own implicit
                # transaction, executed in subkey order.
                self._sql(test, "\n".join(
                    f"INSERT INTO {_seq_table(k)} (key) VALUES ('{k}');"
                    for k in ks))
                return {**op, "type": "ok"}
            seen = []
            for k in reversed(ks):
                out = self._sql(
                    test,
                    f"SELECT key FROM {_seq_table(k)} WHERE key = '{k}';")
                rows = [line for line in out.strip().split("\n")
                        if line.strip() == k]
                seen.append(k if rows else None)
            return {**op, "type": "ok", "value": [op["value"], seen]}
        except c.RemoteError as e:
            if _is_serialization_error(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise


def sequential_gen(n_writers: int = 3):
    """Sequential integer write keys; reads sample the last 2n written
    (sequential.clj:109-133)."""
    last = deque(maxlen=2 * n_writers)
    lock = threading.Lock()
    ctr = itertools.count()

    def write(t=None, ctx=None):
        k = next(ctr)
        with lock:
            last.append(k)
        return {"type": "invoke", "f": "write", "value": k}

    def read(t=None, ctx=None):
        with lock:
            pool = list(last)
        # Nothing written yet: probe key 0 (an all-None read is legal).
        k = pool[gen.rand_int(len(pool))] if pool else 0
        return {"type": "invoke", "f": "read", "value": k}

    return gen.reserve(n_writers, write, read)


def _trailing_nil(seen) -> bool:
    return any(v is None for v in
               itertools.dropwhile(lambda v: v is None, seen))


def sequential_checker() -> jchecker.Checker:
    """A read [k, [newest … oldest]] must never observe a later subkey
    without every earlier one: a None after a non-None is a sequential
    violation (sequential.clj:135-154)."""

    def chk(test, history, opts):
        bad, counts = [], Counter()
        for op in history:
            if not (op.is_ok and op.f == "read"):
                continue
            k, seen = op.value
            if all(v is None for v in seen):
                counts["none"] += 1
            elif any(v is None for v in seen):
                counts["some"] += 1
            else:
                counts["all"] += 1
            if _trailing_nil(seen):
                bad.append({"key": k, "reads": seen})
        return {
            "valid": not bad,
            "bad-count": len(bad),
            "all-count": counts["all"],
            "some-count": counts["some"],
            "none-count": counts["none"],
            "bad": bad,
        }

    return jchecker.checker_fn(chk, "sequential")


# --- comments (cockroach/comments.clj) -------------------------------------


def _comment_table(id_: int) -> str:
    return f"{COMMENT_PREFIX}{zlib.crc32(str(id_).encode()) % COMMENT_TABLES}"


class CommentsClient(_SqlClient):
    """Blind sharded inserts + cross-table txn reads
    (comments.clj:42-90): finds T1 < T2 where T2 is visible without T1
    — the strict-serializability "comment ordering" anomaly."""

    def setup(self, test):
        self._sql(test, "\n".join(
            f"CREATE TABLE IF NOT EXISTS {COMMENT_PREFIX}{i} "
            "(id INT PRIMARY KEY, key INT);"
            for i in range(COMMENT_TABLES)))

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "write":
                self._sql(test,
                          f"INSERT INTO {_comment_table(v)} (id, key) "
                          f"VALUES ({v}, {k});")
                return {**op, "type": "ok"}
            stmts = ["BEGIN;"] + [
                f"SELECT id FROM {COMMENT_PREFIX}{i} WHERE key = {k};"
                for i in range(COMMENT_TABLES)
            ] + ["COMMIT;"]
            out = self._sql(test, "\n".join(stmts))
            ids = sorted(int(r[0]) for r in _tsv_rows(out, 1))
            return {**op, "type": "ok", "value": independent.tuple_(k, ids)}
        except c.RemoteError as e:
            if _is_serialization_error(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise


def comments_checker() -> jchecker.Checker:
    """Replay: expected[w] = writes completed before w's invocation; a
    read seeing w but missing some of expected[w] violates strict
    serializability (comments.clj:92-141). Per-key under
    independent.checker."""

    def chk(test, history, opts):
        completed: set = set()
        expected: dict = {}
        for op in history:
            if op.f != "write":
                continue
            if op.is_invoke:
                expected[op.value] = frozenset(completed)
            elif op.is_ok:
                completed.add(op.value)
        errors = []
        for op in history:
            if not (op.is_ok and op.f == "read"):
                continue
            seen = set(op.value or [])
            want: set = set()
            for v in seen:
                want |= expected.get(v, frozenset())
            missing = want - seen
            if missing:
                errors.append({"op": repr(op),
                               "missing": sorted(missing),
                               "expected-count": len(want)})
        return {"valid": not errors, "errors": errors}

    return jchecker.checker_fn(chk, "comments")


class G2Client(_SqlClient):
    """Adya G2 predicate pairs (cockroach/adya.clj:24-87): the
    reference's read-then-insert collapses to one
    INSERT…WHERE NOT EXISTS over both tables; no returned row means the
    other transaction already committed (:fail :too-late)."""

    def setup(self, test):
        self._sql(test, "\n".join(
            f"CREATE TABLE IF NOT EXISTS {G2_PREFIX}{t} "
            "(id INT PRIMARY KEY, key INT, value INT);" for t in ("a", "b")))

    def invoke(self, test, op):
        k, ids = op["value"]
        a_id, b_id = ids
        table = "a" if a_id is not None else "b"
        id_ = a_id if a_id is not None else b_id
        guard = " AND ".join(
            f"NOT EXISTS (SELECT 1 FROM {G2_PREFIX}{t} "
            f"WHERE key = {k} AND value % 3 = 0)" for t in ("a", "b"))
        try:
            out = self._sql(
                test,
                f"INSERT INTO {G2_PREFIX}{table} (id, key, value) "
                f"SELECT {id_}, {k}, 30 WHERE {guard} RETURNING id;")
            if _tsv_rows(out, 1):
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": "too-late"}
        except c.RemoteError as e:
            if _is_serialization_error(e):
                return {**op, "type": "fail", "error": "serialization"}
            raise


class CockroachDB(jdb.DB, jdb.Process, jdb.LogFiles):
    DIR = "/opt/cockroach"
    LOG = "/var/log/cockroach.log"
    PID = "/var/run/cockroach.pid"

    def __init__(self, version: str = "23.1.11"):
        self.version = version

    def setup(self, test, node):
        url = (f"https://binaries.cockroachdb.com/"
               f"cockroach-v{self.version}.linux-amd64.tgz")
        cu.install_archive(url, self.DIR)
        self.start(test, node)

    def start(self, test, node):
        joins = ",".join(test["nodes"])
        with c.su():
            cu.start_daemon(
                {"logfile": self.LOG, "pidfile": self.PID, "chdir": self.DIR},
                f"{self.DIR}/cockroach",
                "start", "--insecure",
                "--advertise-addr", node,
                "--join", joins,
                "--store", "/var/lib/cockroach",
            )
        if node == test["nodes"][0]:
            try:
                c.exec_star(
                    f"{self.DIR}/cockroach init --insecure --host={node}")
            except c.RemoteError as e:
                # Re-init of an initialized cluster is expected; anything
                # else should be visible in the logs.
                if "already" not in str(e):
                    import logging

                    logging.getLogger("jepsen.cockroachdb").warning(
                        "cockroach init failed: %s", e)

    def kill(self, test, node):
        cu.grepkill("cockroach")

    def teardown(self, test, node):
        cu.grepkill("cockroach")
        with c.su():
            c.exec("rm", "-rf", "/var/lib/cockroach", self.PID)

    def log_files(self, test, node):
        return [self.LOG]


def bank_workload(opts: dict) -> dict:
    wl = wbank.test(opts)
    return {**wl, "client": BankClient()}


def append_workload(opts: dict) -> dict:
    wl = wa.test({"key_count": 4})
    return {"client": AppendClient(), "generator": wl["generator"],
            "checker": wl["checker"]}


def register_workload(opts: dict) -> dict:
    wl = wreg.test(opts)
    return {**wl, "client": RegisterClient()}


def sets_workload(opts: dict) -> dict:
    ids = itertools.count()

    def add(t=None, ctx=None):
        return {"type": "invoke", "f": "add", "value": next(ids)}

    return {
        "client": SetsClient(),
        "generator": gen.stagger(0.05, add),
        "final-generator": gen.once(
            {"type": "invoke", "f": "read", "value": None}),
        "checker": jchecker.compose({
            "set": jchecker.set_full(),
            "stats": jchecker.stats(),
        }),
    }


def monotonic_workload(opts: dict) -> dict:
    keys = list(range(int(opts.get("keys") or 2)))

    def fgen(k):
        return gen.stagger(
            0.05, lambda t=None, ctx=None:
            {"type": "invoke", "f": "add", "value": None})

    def fgen_final(k):
        return gen.limit(1, lambda t=None, ctx=None:
                         {"type": "invoke", "f": "read", "value": None})

    return {
        "client": MonotonicClient(keys=keys),
        "generator": independent.concurrent_generator(2, list(keys), fgen),
        "final-generator": independent.concurrent_generator(
            2, list(keys), fgen_final),
        "checker": independent.checker(jchecker.compose({
            "monotonic": check_monotonic(),
            "stats": jchecker.stats(),
        })),
    }


def sequential_workload(opts: dict) -> dict:
    key_count = int(opts.get("key-count") or 5)
    return {
        "client": SequentialClient(key_count=key_count),
        "generator": gen.stagger(0.02, sequential_gen()),
        "checker": jchecker.compose({
            "sequential": sequential_checker(),
            "stats": jchecker.stats(),
        }),
    }


def comments_workload(opts: dict) -> dict:
    ids = itertools.count()
    lock = threading.Lock()

    def write(t=None, ctx=None):
        with lock:
            v = next(ids)
        return {"type": "invoke", "f": "write", "value": v}

    def read(t=None, ctx=None):
        return {"type": "invoke", "f": "read", "value": None}

    def fgen(k):
        return gen.stagger(0.02, gen.mix([read, write]))

    return {
        "client": CommentsClient(),
        "generator": independent.concurrent_generator(
            2, itertools.count(), fgen),
        "checker": independent.checker(jchecker.compose({
            "comments": comments_checker(),
            "stats": jchecker.stats(),
        })),
    }


def g2_workload(opts: dict) -> dict:
    return {
        "client": G2Client(),
        "generator": wadya.g2_gen(),
        "checker": jchecker.compose({
            "g2": wadya.g2_checker(),
            "stats": jchecker.stats(),
        }),
    }


WORKLOADS = {
    "bank": bank_workload,
    "append": append_workload,
    "register": register_workload,
    "sets": sets_workload,
    "monotonic": monotonic_workload,
    "sequential": sequential_workload,
    "comments": comments_workload,
    "g2": g2_workload,
}


def test_fn(opts: dict) -> dict:
    name = opts.get("workload") or "bank"
    wl = WORKLOADS[name](opts)
    db = CockroachDB(str(opts.get("version") or "23.1.11"))
    pkg = ncombined.nemesis_package({
        "db": db,
        "interval": opts.get("nemesis_interval") or 10,
        "faults": (opts.get("faults") or "partition,kill").split(","),
    })
    test = {
        "name": f"cockroachdb-{name}",
        "db": db,
        "net": jnet.iptables(),
        "nemesis": pkg["nemesis"],
        "plot": {"nemeses": pkg["perf"]},
        **{k: v for k, v in wl.items()
           if k not in ("generator", "final-generator")},
    }
    # Time-limit the WHOLE nemesis+client composite: nemesis-package
    # generators repeat on an interval forever and would otherwise keep
    # the phase alive after the client generator exhausts. Workloads
    # with a final read (sets/monotonic) get a fault-free phase after
    # the heal.
    phases = [
        gen.time_limit(
            opts.get("time_limit", 60),
            gen.nemesis(pkg["generator"], wl["generator"])),
        gen.nemesis(pkg["final-generator"]),
    ]
    if wl.get("final-generator") is not None:
        phases.append(gen.clients(wl["final-generator"]))
    test["generator"] = gen.phases(*phases)
    return test


def _add_opts(p):
    p.add_argument("--workload", choices=sorted(WORKLOADS), default="bank")
    p.add_argument("--version", default="23.1.11")
    p.add_argument("--faults", default="partition,kill")
    p.add_argument("--nemesis-interval", type=int, default=10)


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn, add_opts=_add_opts), argv)


if __name__ == "__main__":
    main()
