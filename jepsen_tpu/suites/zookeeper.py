"""ZooKeeper CAS-register suite.

Mirrors the reference zookeeper suite (zookeeper/src/jepsen/
zookeeper.clj:106-137): a single CAS register (the reference uses an
avout zk-atom; here the znode's version-guarded setData gives CAS), with
Debian install + myid/zoo.cfg provisioning. The client drives
``zkCli.sh`` on the node through the control session.
"""

from __future__ import annotations

import re
from typing import Any, Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from .. import nemesis as jnemesis, net as jnet
from ..control import util as cu
from ..models import CasRegister
from .. import control as c
from . import std_generator

ZNODE = "/jepsen"


class ZkClient(jclient.Client):
    """CAS via version-guarded set: get returns (value, version); set -v
    guards on it."""

    def __init__(self, node: Any = None):
        self.node = node

    def open(self, test, node):
        return ZkClient(node)

    def setup(self, test):
        self._zk(test, f"create {ZNODE} 0", ignore_errors=True)

    def _zk(self, test, cmd: str, ignore_errors: bool = False) -> str:
        def run(t, node):
            try:
                return c.exec_star(
                    f"/usr/share/zookeeper/bin/zkCli.sh -server "
                    f"127.0.0.1:2181 {c.escape(cmd)} 2>&1")
            except c.RemoteError:
                if ignore_errors:
                    return ""
                raise

        return c.on_nodes(test, run, [self.node])[self.node]

    def _get(self, test):
        out = self._zk(test, f"get -s {ZNODE}")
        lines = [l for l in out.split("\n") if l.strip()]
        # zkCli `get -s` prints the data first, then the stat block
        # starting at cZxid; the register value is the data line.
        version = None
        data_end = None
        for i, l in enumerate(lines):
            if data_end is None and l.startswith("cZxid"):
                data_end = i
            m = re.match(r"dataVersion = (\d+)", l)
            if m:
                version = int(m.group(1))
        if version is None or data_end is None:
            raise RuntimeError(f"unparseable zk get: {out!r}")
        data = [l for l in lines[:data_end] if re.fullmatch(r"-?\d+", l)]
        value = int(data[-1]) if data else None
        return value, version

    def invoke(self, test, op):
        f = op["f"]
        try:
            if f == "read":
                v, _ver = self._get(test)
                return {**op, "type": "ok", "value": v}
            if f == "write":
                self._zk(test, f"set {ZNODE} {op['value']}")
                return {**op, "type": "ok"}
            if f == "cas":
                old, new = op["value"]
                v, ver = self._get(test)
                if v != old:
                    return {**op, "type": "fail"}
                try:
                    self._zk(test, f"set -v {ver} {ZNODE} {new}")
                    return {**op, "type": "ok"}
                except c.RemoteError:
                    return {**op, "type": "fail"}
            raise ValueError(f"unknown f {f!r}")
        except Exception:
            if f == "read":
                return {**op, "type": "fail", "error": "zk"}
            raise


class ZookeeperDB(jdb.DB, jdb.Process, jdb.LogFiles):
    """zookeeper/src/jepsen/zookeeper.clj:30-70: apt install, myid,
    zoo.cfg with one server line per node, restart."""

    def setup(self, test, node):
        from ..os_ import debian

        debian.install(["zookeeper", "zookeeperd"])
        myid = test["nodes"].index(node) + 1
        with c.su():
            c.exec_star(f"echo {myid} > /etc/zookeeper/conf/myid")
            servers = "\n".join(
                f"server.{i + 1}={n}:2888:3888"
                for i, n in enumerate(test["nodes"]))
            c.exec_star(
                "cat > /etc/zookeeper/conf/zoo.cfg <<'JEPSEN_EOF'\n"
                "tickTime=2000\ninitLimit=10\nsyncLimit=5\n"
                "dataDir=/var/lib/zookeeper\nclientPort=2181\n"
                f"{servers}\nJEPSEN_EOF")
        self.start(test, node)

    def start(self, test, node):
        with c.su():
            c.exec("service", "zookeeper", "restart")

    def kill(self, test, node):
        cu.grepkill("zookeeper")

    def teardown(self, test, node):
        with c.su():
            c.exec_star("service zookeeper stop || true")
            c.exec("rm", "-rf", "/var/lib/zookeeper/version-2")

    def log_files(self, test, node):
        return ["/var/log/zookeeper/zookeeper.log"]


def test_fn(opts: dict) -> dict:
    def w(test=None, ctx=None):
        return {"type": "invoke", "f": "write", "value": gen.rand_int(5)}

    def r(test=None, ctx=None):
        return {"type": "invoke", "f": "read", "value": None}

    def cas(test=None, ctx=None):
        return {"type": "invoke", "f": "cas",
                "value": [gen.rand_int(5), gen.rand_int(5)]}

    return {
        "name": "zookeeper",
        "db": ZookeeperDB(),
        "net": jnet.iptables(),
        "nemesis": jnemesis.partition_random_halves(),
        "client": ZkClient(),
        "checker": jchecker.compose({
            "linear": jchecker.linearizable(model=CasRegister(init=0)),
            "stats": jchecker.stats(),
        }),
        "generator": std_generator(
            opts, gen.stagger(0.1, gen.mix([r, w, cas]))),
    }


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn), argv)


if __name__ == "__main__":
    main()
