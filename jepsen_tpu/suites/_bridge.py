"""Shared newline-delimited bridge-client mechanics.

Several suites talk to a node-side bridge daemon (hazelcast's CP
bridge, aerospike's generation-guarded bridge, ignite's transactional
bridge) over the same one-line-request / one-line-reply protocol; this
is the single socket + framing + ERR-handling implementation they all
ride."""

from __future__ import annotations

import socket
from typing import Any


class LineProto:
    """One bridge connection: ``roundtrip`` sends a space-joined
    command line and returns the reply's tokens, raising RuntimeError
    on an ``ERR ...`` reply."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.buf = b""

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass

    def roundtrip(self, parts: tuple[Any, ...], maxsplit: int = -1) -> list:
        """``maxsplit`` bounds reply tokenization (JSON payloads with
        spaces ride a maxsplit=1 reply)."""
        self.sock.sendall((" ".join(str(p) for p in parts) + "\n").encode())
        while b"\n" not in self.buf:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise ConnectionError("bridge closed connection")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\n", 1)
        words = line.decode().strip().split(" ", maxsplit) if maxsplit >= 0 \
            else line.decode().strip().split()
        if not words or words[0] == "ERR":
            raise RuntimeError(" ".join(words[1:]) or "bridge error")
        return words
