"""Shared newline-delimited bridge-client mechanics.

Several suites talk to a node-side bridge daemon (hazelcast's CP
bridge, aerospike's generation-guarded bridge, ignite's transactional
bridge) over the same one-line-request / one-line-reply protocol; this
is the single socket + framing + ERR-handling implementation they all
ride."""

from __future__ import annotations

import socket
from typing import Any

from .. import client as jclient


class LineProto:
    """One bridge connection: ``roundtrip`` sends a space-joined
    command line and returns the reply's tokens, raising RuntimeError
    on an ``ERR ...`` reply."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.buf = b""

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass

    def roundtrip(self, parts: tuple[Any, ...], maxsplit: int = -1) -> list:
        """``maxsplit`` bounds reply tokenization (JSON payloads with
        spaces ride a maxsplit=1 reply)."""
        self.sock.sendall((" ".join(str(p) for p in parts) + "\n").encode())
        while b"\n" not in self.buf:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise ConnectionError("bridge closed connection")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\n", 1)
        words = line.decode().strip().split(" ", maxsplit) if maxsplit >= 0 \
            else line.decode().strip().split()
        if not words or words[0] == "ERR":
            raise RuntimeError(" ".join(words[1:]) or "bridge error")
        return words


class BridgeClient(jclient.Client):
    """Connection lifecycle + socket-fault mapping shared by the
    bridge-speaking workload clients (aerospike cas-register/counter,
    ignite bank). Subclasses set ``PROTO`` (a LineProto subclass taking
    one host argument) and implement ``invoke`` with ``self._conn()``
    for the lazy connection and ``self._fault(op, e)`` in the socket
    except-arm."""

    PROTO: type = LineProto

    def __init__(self, conn: Any = None, node: Any = None):
        self.conn = conn
        self.node = node

    def open(self, test, node):
        return type(self)(type(self).PROTO(str(node)), node)

    def _conn(self):
        if self.conn is None:
            self.conn = type(self).PROTO(str(self.node))
        return self.conn

    def _drop_conn(self):
        """Always tear the connection down on a socket fault: a request
        may still be in flight, and reusing the socket would pair the
        NEXT command with THIS op's late reply."""
        if self.conn is not None:
            self.conn.close()
            self.conn = None

    def _fault(self, op, e):
        """Socket faults are definite :fail for reads (no state moved)
        and indeterminate :info for mutations."""
        self._drop_conn()
        kind = "fail" if op["f"] == "read" else "info"
        return {**op, "type": kind, "error": str(e)[:80]}

    def close(self, test):
        if self.conn is not None:
            self.conn.close()
