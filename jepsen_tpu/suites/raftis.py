"""Raftis suite: a Raft-replicated redis-protocol register.

The reference's raftis suite (raftis/, 158 LoC — the smallest in the
monorepo) drives a toy Raft KV store speaking RESP with a plain
read/write register checked for linearizability. This suite reuses the
RESP client from the redis suite (GET/SET only — raftis has no EVAL, so
no CAS arm) against the device-checked register model.
"""

from __future__ import annotations

from typing import Any, Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from .. import nemesis as jnemesis, net as jnet
from ..control import util as cu
from ..models import Register
from .redis import Resp
from .. import control as c
from . import std_generator

PORT = 6379
KEY = "jepsen"


class RegisterClient(jclient.Client):
    def __init__(self, conn: Optional[Resp] = None):
        self.conn = conn

    def open(self, test, node):
        return RegisterClient(Resp(str(node), PORT))

    def invoke(self, test, op):
        if op["f"] == "read":
            raw = self.conn.cmd("GET", KEY)
            return {**op, "type": "ok",
                    "value": None if raw is None else int(raw)}
        if op["f"] == "write":
            self.conn.cmd("SET", KEY, op["value"])
            return {**op, "type": "ok"}
        raise ValueError(f"unknown f {op['f']!r}")

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


class RaftisDB(jdb.DB, jdb.Process, jdb.LogFiles):
    DIR = "/opt/raftis"
    LOG = "/var/log/raftis.log"

    def setup(self, test, node):
        cu.install_archive(
            "https://github.com/goraft/raftis/archive/master.tar.gz",
            self.DIR)
        self.start(test, node)

    def start(self, test, node):
        peers = ",".join(f"{n}:7000" for n in test["nodes"])
        with c.su():
            cu.start_daemon(
                {"logfile": self.LOG, "pidfile": "/var/run/raftis.pid",
                 "chdir": self.DIR},
                f"{self.DIR}/raftis",
                "-bind", f"{node}:7000",
                "-peers", peers,
                "-port", PORT,
            )

    def kill(self, test, node):
        cu.grepkill("raftis")

    def teardown(self, test, node):
        cu.grepkill("raftis")
        with c.su():
            c.exec_star("rm -rf /var/lib/raftis")

    def log_files(self, test, node):
        return [self.LOG]


def register_workload(opts: Optional[dict] = None) -> dict:
    def r(test=None, ctx=None):
        return {"type": "invoke", "f": "read", "value": None}

    def w(test=None, ctx=None):
        return {"type": "invoke", "f": "write", "value": gen.rand_int(5)}

    return {
        "client": RegisterClient(),
        "checker": jchecker.compose({
            "linear": jchecker.linearizable(model=Register(init=None)),
            "stats": jchecker.stats(),
        }),
        "generator": gen.stagger(0.1, gen.mix([r, w])),
    }


def test_fn(opts: dict) -> dict:
    wl = register_workload(opts)
    return {
        "name": "raftis",
        "db": RaftisDB(),
        "net": jnet.iptables(),
        "nemesis": jnemesis.partition_random_halves(),
        **{k: v for k, v in wl.items() if k != "generator"},
        "generator": std_generator(opts, wl["generator"]),
    }


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn), argv)


if __name__ == "__main__":
    main()
