"""CrateDB suite: dirty-read / lost-updates / version-divergence.

The reference's crate suite (crate/, 1157 LoC, SURVEY §2.6) probes
Elasticsearch-backed SQL for three anomalies, each with its own checker:

- **dirty-read**: a read observing a row whose insert was never
  acknowledged committed (reads of uncommitted state);
- **lost-updates**: acknowledged inserts missing from the final
  read-all;
- **version-divergence**: CrateDB exposes a ``_version`` column per
  row; two reads observing the SAME version with DIFFERENT values mean
  replicas diverged under one version number — the suite's signature
  anomaly.

Clients speak the HTTP ``/_sql`` endpoint (JSON stmt/args — the real
CrateDB wire surface, no driver)."""

from __future__ import annotations

import json
import urllib.request
from typing import Any, Optional

from .. import checker as jchecker
from .. import cli, client as jclient, db as jdb, generator as gen
from ..checker import Checker, checker_fn
from ..control import util as cu
from .. import nemesis as jnemesis, net as jnet
from .. import control as c
from . import std_generator

PORT = 4200
TABLE = "jepsen_dirty"


class Sql:
    """Minimal /_sql client."""

    def __init__(self, host: str, port: Optional[int] = None,
                 timeout: float = 10.0):
        if port is None:
            port = PORT
        self.base = f"http://{host}:{port}/_sql"
        self.timeout = timeout

    def stmt(self, stmt: str, args: Optional[list] = None) -> dict:
        body = {"stmt": stmt}
        if args is not None:
            body["args"] = args
        req = urllib.request.Request(
            self.base, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read().decode())


class DirtyReadClient(jclient.Client):
    """write → insert one row id; read → select a row by id; read-all →
    final refresh + full scan (crate/src/jepsen/crate/dirty_read.clj
    semantics)."""

    def __init__(self, conn: Optional[Sql] = None):
        self.conn = conn

    def open(self, test, node):
        return DirtyReadClient(Sql(str(node)))

    def setup(self, test):
        self.conn.stmt(
            f"CREATE TABLE IF NOT EXISTS {TABLE} "
            "(id BIGINT PRIMARY KEY) "
            "WITH (number_of_replicas = 2)")

    def invoke(self, test, op):
        if op["f"] == "write":
            self.conn.stmt(f"INSERT INTO {TABLE} (id) VALUES (?)",
                           [op["value"]])
            return {**op, "type": "ok"}
        if op["f"] == "read":
            try:
                res = self.conn.stmt(
                    f"SELECT id FROM {TABLE} WHERE id = ?", [op["value"]])
            except Exception:
                return {**op, "type": "fail", "error": "http"}
            rows = res.get("rows") or []
            if rows:
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": "not-found"}
        if op["f"] == "read-all":
            try:
                self.conn.stmt(f"REFRESH TABLE {TABLE}")
                res = self.conn.stmt(f"SELECT id FROM {TABLE}")
            except Exception:
                return {**op, "type": "fail", "error": "http"}
            return {**op, "type": "ok",
                    "value": sorted(r[0] for r in res.get("rows") or [])}
        raise ValueError(f"unknown f {op['f']!r}")

    def close(self, test):
        pass


class VersionClient(jclient.Client):
    """update → set one register row's value; read → (_version, value)
    pairs (crate/src/jepsen/crate/lost_updates.clj + version checks)."""

    def __init__(self, conn: Optional[Sql] = None):
        self.conn = conn

    def open(self, test, node):
        return VersionClient(Sql(str(node)))

    def setup(self, test):
        self.conn.stmt(
            "CREATE TABLE IF NOT EXISTS jepsen_version "
            "(id INT PRIMARY KEY, v BIGINT) "
            "WITH (number_of_replicas = 2)")
        try:
            self.conn.stmt(
                "INSERT INTO jepsen_version (id, v) VALUES (0, 0)")
        except Exception:  # noqa: BLE001 - already inserted
            pass

    def invoke(self, test, op):
        if op["f"] == "update":
            self.conn.stmt(
                "UPDATE jepsen_version SET v = ? WHERE id = 0",
                [op["value"]])
            return {**op, "type": "ok"}
        if op["f"] == "read":
            try:
                res = self.conn.stmt(
                    "SELECT _version, v FROM jepsen_version WHERE id = 0")
            except Exception:
                return {**op, "type": "fail", "error": "http"}
            rows = res.get("rows") or []
            if not rows:
                return {**op, "type": "fail", "error": "not-found"}
            version, v = rows[0]
            return {**op, "type": "ok", "value": [version, v]}
        raise ValueError(f"unknown f {op['f']!r}")

    def close(self, test):
        pass


class CrateDB(jdb.DB, jdb.Process, jdb.LogFiles):
    LOG = "/var/log/crate/crate.log"

    def setup(self, test, node):
        from ..os_ import debian

        debian.install(["crate"])
        hosts = json.dumps([f"{n}:4300" for n in test["nodes"]])
        with c.su():
            c.exec_star(
                "cat > /etc/crate/crate.yml <<'JEPSEN_EOF'\n"
                "cluster.name: jepsen\n"
                f"node.name: {node}\n"
                "network.host: 0.0.0.0\n"
                f"discovery.seed_hosts: {hosts}\n"
                f"cluster.initial_master_nodes: "
                f"{json.dumps(test['nodes'])}\n"
                "JEPSEN_EOF")
        self.start(test, node)

    def start(self, test, node):
        with c.su():
            c.exec("service", "crate", "start")

    def kill(self, test, node):
        cu.grepkill("crate")

    def teardown(self, test, node):
        with c.su():
            c.exec_star("service crate stop || true")
            c.exec_star("rm -rf /var/lib/crate/*")

    def log_files(self, test, node):
        return [self.LOG]


def dirty_read_checker() -> Checker:
    """crate dirty-read semantics: reads must only observe acknowledged
    writes (a read-ok of an id that was never write-ok = dirty); acked
    writes must survive to the final read-all (else lost)."""

    def chk(test, history, opts):
        acked = set()
        invoked = set()
        dirty = []
        finals = []
        for op in history:
            if op.f == "write":
                if op.is_invoke:
                    invoked.add(op.value)
                elif op.is_ok:
                    acked.add(op.value)
            elif op.f == "read" and op.is_ok:
                if op.value not in invoked:
                    dirty.append(op.value)
            elif op.f == "read-all" and op.is_ok:
                finals.append(set(op.value or []))
        final = set.union(*finals) if finals else set()
        lost = sorted(acked - final) if finals else []
        # Reads of ids that were invoked but never acked: these are
        # *dirty* only if the write ultimately failed; indeterminate
        # writes that later show up are fine.
        return {
            "valid": not dirty and not lost,
            "acked_count": len(acked),
            "dirty": sorted(dirty),
            "lost": lost,
            "final_count": len(final) if finals else None,
        }

    return checker_fn(chk, "dirty-read")


def version_divergence_checker() -> Checker:
    """Two ok reads with the same _version but different values mean the
    replicas diverged under one version number."""

    def chk(test, history, opts):
        seen = {}
        divergent = {}
        for op in history:
            if op.f == "read" and op.is_ok and op.value:
                version, v = op.value
                if version in seen and seen[version] != v:
                    divergent.setdefault(version, set()).update(
                        {seen[version], v})
                else:
                    seen.setdefault(version, v)
        return {
            "valid": not divergent,
            "versions_read": len(seen),
            "divergent": {k: sorted(vs) for k, vs in divergent.items()},
        }

    return checker_fn(chk, "version-divergence")


def dirty_read_workload(opts: Optional[dict] = None) -> dict:
    o = dict(opts or {})
    counter = [0]

    def write(test=None, ctx=None):
        counter[0] += 1
        return {"type": "invoke", "f": "write", "value": counter[0]}

    def read(test=None, ctx=None):
        return {"type": "invoke", "f": "read",
                "value": gen.rand_int(max(counter[0], 1)) + 1}

    load = gen.clients(gen.limit(int(o.get("ops") or 200),
                                 gen.mix([write, read, read])))
    final = gen.clients(gen.once({"type": "invoke", "f": "read-all",
                                  "value": None}))
    return {
        "client": DirtyReadClient(),
        "checker": jchecker.compose({
            "dirty-read": dirty_read_checker(),
            "stats": jchecker.stats(),
        }),
        "generator": gen.phases(load, final),
        "load-generator": load,
        "final-generator": final,
    }


def version_workload(opts: Optional[dict] = None) -> dict:
    o = dict(opts or {})
    counter = [0]

    def update(test=None, ctx=None):
        counter[0] += 1
        return {"type": "invoke", "f": "update", "value": counter[0]}

    def read(test=None, ctx=None):
        return {"type": "invoke", "f": "read", "value": None}

    load = gen.clients(gen.limit(int(o.get("ops") or 200),
                                 gen.mix([update, read])))
    return {
        "client": VersionClient(),
        "checker": jchecker.compose({
            "version-divergence": version_divergence_checker(),
            "stats": jchecker.stats(),
        }),
        "generator": load,
        "load-generator": load,
    }


WORKLOADS = {"dirty-read": dirty_read_workload,
             "version-divergence": version_workload}


def test_fn(opts: dict) -> dict:
    name = opts.get("workload") or "dirty-read"
    wl = WORKLOADS[name](opts)
    return {
        "name": f"crate-{name}",
        "db": CrateDB(),
        "net": jnet.iptables(),
        "nemesis": jnemesis.partition_random_halves(),
        **{k: v for k, v in wl.items()
           if k not in ("generator", "load-generator", "final-generator")},
        "generator": std_generator(
            opts, wl["load-generator"],
            final_client_gen=wl.get("final-generator")),
    }


def _add_opts(p):
    p.add_argument("--workload", choices=sorted(WORKLOADS),
                   default="dirty-read")
    p.add_argument("--ops", type=int, default=200)


def main(argv=None):
    cli.main_exit(cli.single_test_cmd(test_fn, add_opts=_add_opts), argv)


if __name__ == "__main__":
    main()
