"""Control plane: run commands on cluster nodes.

Mirrors jepsen.control (jepsen/src/jepsen/control.clj):

- :class:`Remote` protocol — connect/disconnect/execute/upload/download
  (control.clj:18-35).
- Ambient per-thread session state (host, dir, sudo, trace — the
  reference's dynamic vars, control.clj:37-49) so node-side code reads as
  ``c.exec("iptables", "-F")`` inside an :func:`on_nodes` callback.
- Shell escaping rules ported from control.clj:77-120 (:func:`escape`,
  :class:`Lit` literals, ``|`` pipes, ``>``/``>>``/``<`` redirections).
- Backends: :class:`SshRemote` (OpenSSH client subprocess — the JSch
  analogue), :class:`ShellRemote` (localhost subprocess), and
  :class:`DummyRemote` (records commands, returns canned results — the
  ``:dummy?`` mode, control.clj:38,317-331, which unlocks cluster-free
  integration tests). docker/k8s exec variants live in
  `jepsen_tpu.control.docker`.

Sessions auto-reconnect with bounded retries (reconnect.clj:92-129
semantics folded into :class:`Session`).
"""

from __future__ import annotations

import logging
import re
import shutil
import subprocess
import threading
import time
from typing import Any, Callable, Iterable, Optional

from ..util import real_pmap

LOG = logging.getLogger("jepsen.control")


class Lit:
    """A literal string passed unescaped to the shell (control.clj:66-75)."""

    __slots__ = ("string",)

    def __init__(self, s: str):
        self.string = s

    def __repr__(self):
        return f"(lit {self.string!r})"


PIPE = Lit("|")
AMP = Lit("&&")

_NEEDS_QUOTES = re.compile(r"[\\\$`\"\s\(\)\{\}\[\]\*\?<>&;|~#!]")
_ESCAPE_CHARS = re.compile(r"([\\\$`\"])")


def escape(x: Any) -> str:
    """Escape a thing for the shell (control.clj:77-120): None -> "",
    literals pass through, ">", ">>", "<" are redirections, sequences are
    escaped element-wise and space-joined."""
    if x is None:
        return ""
    if isinstance(x, Lit):
        return x.string
    if isinstance(x, (list, tuple, set, frozenset)):
        return " ".join(escape(e) for e in x)
    s = str(x)
    if s in (">", ">>", "<"):
        return s
    if s == "":
        return '""'
    if _NEEDS_QUOTES.search(s):
        return '"' + _ESCAPE_CHARS.sub(r"\\\1", s) + '"'
    return s


# ---------------------------------------------------------------------------
# Ambient state (the reference's dynamic vars, control.clj:37-49)


class _Env(threading.local):
    def __init__(self):
        self.host = None
        self.session = None
        self.dir = "/"
        self.sudo = None
        self.trace = False
        self.ssh = {}


_env = _Env()


class _Binding:
    def __init__(self, **kw):
        self.kw = kw
        self.prev = {}

    def __enter__(self):
        for k, v in self.kw.items():
            self.prev[k] = getattr(_env, k)
            setattr(_env, k, v)
        return self

    def __exit__(self, *exc):
        for k, v in self.prev.items():
            setattr(_env, k, v)
        return False


def su():
    """Run body as root (control.clj:280-290)."""
    return _Binding(sudo="root")


def sudo(user: str):
    return _Binding(sudo=user)


def cd(dir: str):
    return _Binding(dir=dir)


def trace():
    return _Binding(trace=True)


def with_ssh(conf: dict):
    """Bind SSH config for the body (control.clj:383-401)."""
    return _Binding(ssh=dict(conf or {}))


def with_session(host: Any, session: "Session"):
    return _Binding(host=host, session=session)


def current_host():
    return _env.host


# ---------------------------------------------------------------------------
# Remote protocol + backends


class RemoteError(Exception):
    def __init__(self, result: dict):
        self.result = result
        super().__init__(
            f"Command exited with non-zero status {result.get('exit')} on "
            f"node {result.get('host')}:\n{result.get('cmd')}\n\n"
            f"STDOUT:\n{result.get('out')}\n\nSTDERR:\n{result.get('err')}"
        )


class Remote:
    """control.clj:18-35."""

    def connect(self, host: Any) -> "Remote":
        return self

    def disconnect(self) -> None:
        pass

    def execute(self, action: dict) -> dict:
        """action = {"cmd": str, "in": optional stdin}; returns
        {"out", "err", "exit"}."""
        raise NotImplementedError

    def upload(self, local_paths, remote_path) -> None:
        raise NotImplementedError

    def download(self, remote_paths, local_path) -> None:
        raise NotImplementedError


class DummyRemote(Remote):
    """No-op remote recording every action (the :dummy? mode). A shared
    ``log`` lists (host, cmd) tuples; ``responses`` maps regexes to canned
    stdout."""

    def __init__(self, log: Optional[list] = None,
                 responses: Optional[dict] = None, host: Any = None):
        self.log = log if log is not None else []
        self.responses = responses or {}
        self.host = host

    def connect(self, host):
        return DummyRemote(self.log, self.responses, host)

    def execute(self, action):
        self.log.append((self.host, action["cmd"]))
        out = ""
        for pat, resp in self.responses.items():
            if re.search(pat, action["cmd"]):
                out = resp(self.host, action) if callable(resp) else resp
                break
        return {"out": out, "err": "", "exit": 0}

    def upload(self, local_paths, remote_path):
        self.log.append((self.host, f"<upload {local_paths} -> {remote_path}>"))

    def download(self, remote_paths, local_path):
        self.log.append((self.host, f"<download {remote_paths} -> {local_path}>"))


class ShellRemote(Remote):
    """Executes on the local machine via bash — the no-cluster way to run
    node-side code for real (every "node" is localhost)."""

    def __init__(self, host: Any = None):
        self.host = host

    def connect(self, host):
        return ShellRemote(host)

    def execute(self, action):
        p = subprocess.run(
            ["bash", "-c", action["cmd"]],
            input=(action.get("in") or "").encode() or None,
            capture_output=True,
        )
        return {"out": p.stdout.decode(errors="replace"),
                "err": p.stderr.decode(errors="replace"),
                "exit": p.returncode}

    def upload(self, local_paths, remote_path):
        paths = local_paths if isinstance(local_paths, (list, tuple)) else [
            local_paths]
        for p in paths:
            shutil.copy(str(p), str(remote_path))

    def download(self, remote_paths, local_path):
        paths = remote_paths if isinstance(remote_paths, (list, tuple)) else [
            remote_paths]
        for p in paths:
            shutil.copy(str(p), str(local_path))


class SshRemote(Remote):
    """OpenSSH client subprocess (the clj-ssh/JSch analogue,
    control.clj:298-341). Honors the test's ssh map: username, password
    (via sshpass when present), port, private-key-path,
    strict-host-key-checking."""

    def __init__(self, conf: Optional[dict] = None, host: Any = None):
        self.conf = dict(conf or {})
        self.host = host

    def connect(self, host):
        conf = {**self.conf, **(_env.ssh or {})}
        return SshRemote(conf, host)

    def _base(self, prog: str) -> list:
        conf = self.conf
        cmd = [prog]
        if not conf.get("strict-host-key-checking"):
            cmd += ["-o", "StrictHostKeyChecking=no",
                    "-o", "UserKnownHostsFile=/dev/null"]
        if conf.get("private-key-path"):
            cmd += ["-i", str(conf["private-key-path"])]
        if conf.get("port") and prog == "ssh":
            cmd += ["-p", str(conf["port"])]
        if conf.get("port") and prog == "scp":
            cmd += ["-P", str(conf["port"])]
        return cmd

    def _dest(self) -> str:
        user = self.conf.get("username", "root")
        return f"{user}@{self.host}"

    def execute(self, action):
        argv = self._base("ssh") + [self._dest(), action["cmd"]]
        p = subprocess.run(
            argv,
            input=(action.get("in") or "").encode() or None,
            capture_output=True,
        )
        return {"out": p.stdout.decode(errors="replace"),
                "err": p.stderr.decode(errors="replace"),
                "exit": p.returncode}

    def upload(self, local_paths, remote_path):
        paths = local_paths if isinstance(local_paths, (list, tuple)) else [
            local_paths]
        argv = self._base("scp") + [str(p) for p in paths] + [
            f"{self._dest()}:{remote_path}"]
        p = subprocess.run(argv, capture_output=True)
        if p.returncode:
            raise RemoteError({"cmd": " ".join(argv), "host": self.host,
                               "exit": p.returncode,
                               "err": p.stderr.decode(errors="replace"),
                               "out": ""})

    def download(self, remote_paths, local_path):
        paths = remote_paths if isinstance(remote_paths, (list, tuple)) else [
            remote_paths]
        argv = self._base("scp") + [
            f"{self._dest()}:{p}" for p in paths] + [str(local_path)]
        p = subprocess.run(argv, capture_output=True)
        if p.returncode:
            raise RemoteError({"cmd": " ".join(argv), "host": self.host,
                               "exit": p.returncode,
                               "err": p.stderr.decode(errors="replace"),
                               "out": ""})


def ssh() -> SshRemote:
    return SshRemote()


def dummy(log: Optional[list] = None, responses: Optional[dict] = None
          ) -> DummyRemote:
    return DummyRemote(log, responses)


def shell() -> ShellRemote:
    return ShellRemote()


# ---------------------------------------------------------------------------
# Sessions (auto-reconnecting wrapper; reconnect.clj:16-129 semantics)


class Session:
    """A connection to one node, reopened on failure with bounded retries
    (control.clj:168-189 retry loop + reconnect.clj wrapper)."""

    def __init__(self, remote: Remote, host: Any, retries: int = 5):
        self.remote_proto = remote
        self.host = host
        self.retries = retries
        self.lock = threading.Lock()
        self.conn: Optional[Remote] = None

    def _ensure(self) -> Remote:
        if self.conn is None:
            self.conn = self.remote_proto.connect(self.host)
        return self.conn

    def _with_retry(self, f: Callable) -> Any:
        last = None
        for attempt in range(self.retries):
            try:
                with self.lock:
                    return f(self._ensure())
            except RemoteError:
                raise  # command-level failure; connection is fine
            except Exception as e:  # connection-level: reopen + retry
                last = e
                LOG.warning("session to %s failed (attempt %d); reopening",
                            self.host, attempt + 1)
                with self.lock:
                    try:
                        if self.conn is not None:
                            self.conn.disconnect()
                    except Exception:
                        pass
                    self.conn = None
                time.sleep(min(1.0 + attempt, 3.0))
        raise last

    def execute(self, action: dict) -> dict:
        return self._with_retry(lambda c: c.execute(action))

    def upload(self, local_paths, remote_path):
        return self._with_retry(lambda c: c.upload(local_paths, remote_path))

    def download(self, remote_paths, local_path):
        return self._with_retry(lambda c: c.download(remote_paths, local_path))

    def close(self):
        with self.lock:
            if self.conn is not None:
                try:
                    self.conn.disconnect()
                finally:
                    self.conn = None


# ---------------------------------------------------------------------------
# The ambient command API (exec/su/cd/upload/download)


def _wrap_cd(cmd: str) -> str:
    if _env.dir:
        return f"cd {escape(_env.dir)}; {cmd}"
    return cmd


def _wrap_sudo(action: dict) -> dict:
    if _env.sudo:
        cmd = escape(action["cmd"])
        user = _env.sudo
        # Skip sudo when we're already the target user (e.g. root inside a
        # container without sudo installed).
        return {
            "cmd": (
                f'if [ "$(id -un)" = {user} ]; then bash -c {cmd}; '
                f"else sudo -S -u {user} bash -c {cmd}; fi"
            ),
            "in": action.get("in"),
        }
    return action


def exec_star(*commands: str) -> str:
    """exec without escaping (control.clj:193-203)."""
    cmd = " ".join(str(c) for c in commands)
    action = _wrap_sudo({"cmd": _wrap_cd(cmd)})
    if _env.trace:
        LOG.info("Host: %s cmd: %s", _env.host, action["cmd"])
    session = _env.session
    if session is None:
        raise RuntimeError(
            "Unable to perform a control action: no session bound for this "
            "thread (use on_nodes / with_session)."
        )
    result = session.execute(action)
    result["cmd"] = cmd
    result["host"] = _env.host
    if result.get("exit", 0) != 0:
        raise RemoteError(result)
    return result.get("out", "").rstrip("\n")


def exec(*commands: Any) -> str:
    """Run an escaped shell command on the bound node, returning stdout
    (control.clj:204-210)."""
    return exec_star(*(escape(c) for c in commands))


def upload(local_paths, remote_path):
    _env.session.upload(local_paths, remote_path)
    return remote_path


def download(remote_paths, local_path):
    _env.session.download(remote_paths, local_path)


# ---------------------------------------------------------------------------
# Cluster session management (core.clj:330-338 / control.clj:415-439)


def setup_sessions(test: dict, remote: Optional[Remote] = None) -> dict:
    """Open a Session per node; stores and returns {node: Session} (also
    placed at test["sessions"])."""
    remote = remote or test.get("remote") or ssh()
    if isinstance(remote, Remote):
        proto = remote
    else:
        raise TypeError(f"not a Remote: {remote!r}")
    ssh_conf = test.get("ssh") or {}
    if ssh_conf.get("dummy?") and isinstance(proto, SshRemote):
        proto = DummyRemote(log=test.setdefault("dummy-log", []))
    sessions = {}
    with with_ssh(ssh_conf):
        for node in test.get("nodes") or []:
            sessions[node] = Session(proto, node)
    test["sessions"] = sessions
    return sessions


def close_sessions(sessions: dict) -> None:
    for s in (sessions or {}).values():
        try:
            s.close()
        except Exception:
            LOG.warning("error closing session", exc_info=True)


def on_nodes(test: dict, f: Callable, nodes: Optional[Iterable] = None
             ) -> dict:
    """Run ``f(test, node)`` in parallel on each node with that node's
    session bound (control.clj:415-431). Returns {node: result}."""
    sessions = test.get("sessions") or {}
    target = list(nodes if nodes is not None else (test.get("nodes") or []))

    def run(node):
        session = sessions.get(node)
        if session is None:
            raise RuntimeError(f"No session for node {node!r}")
        with with_session(node, session):
            return (node, f(test, node))

    return dict(real_pmap(run, target))


def with_test_nodes(test: dict, body: Callable) -> dict:
    """Evaluate ``body(node)`` on every node (control.clj:433-439)."""
    return on_nodes(test, lambda t, n: body(n))
