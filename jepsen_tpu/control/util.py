"""Node-side scripting helpers (jepsen.control.util, control/util.clj):
file tests, downloads with caching, archive installs, daemon start/stop,
grepkill. All run through the ambient control session, so they work over
SSH, docker exec, the localhost shell, or the dummy remote alike.
"""

from __future__ import annotations

import logging
from typing import Any, Iterable, Optional

from . import Lit, RemoteError, escape, exec, exec_star, su, upload

LOG = logging.getLogger("jepsen.control.util")

TMP_DIR_BASE = "/tmp/jepsen"


def exists(path: str) -> bool:
    """control/util.clj:20-26."""
    try:
        exec("test", "-e", path)
        return True
    except RemoteError:
        return False


def file_(path: str) -> str:
    return exec("file", path)


def ls(path: str = ".") -> list[str]:
    out = exec("ls", "-1", path)
    return [l for l in out.split("\n") if l]


def ls_full(path: str) -> list[str]:
    """Fully-qualified paths (control/util.clj:34-42)."""
    base = path if path.endswith("/") else path + "/"
    return [base + f for f in ls(path)]

def tmp_dir() -> str:
    """Create and return a fresh temp dir (control/util.clj:44-52)."""
    return exec("mktemp", "-d", "-p", "/tmp", "jepsen.XXXXXX")


def wget(url: str, dest: Optional[str] = None, force: bool = False) -> str:
    """Download url on the node; returns the local filename
    (control/util.clj:54-76)."""
    fname = dest or url.rstrip("/").rsplit("/", 1)[-1]
    if force:
        exec("rm", "-f", fname)
    if not exists(fname):
        exec("wget", "--tries", "20", "--waitretry", "60",
             "--retry-connrefused", "--dns-timeout", "60",
             "--connect-timeout", "60", "--read-timeout", "60",
             "-O", fname, url)
    return fname


CACHE_DIR = "/tmp/jepsen/wget-cache"


def cached_wget(url: str, force: bool = False) -> str:
    """Download url once per node, caching it for future runs
    (control/util.clj:117-147)."""
    fname = url.rstrip("/").rsplit("/", 1)[-1]
    cached = f"{CACHE_DIR}/{fname}"
    if force:
        exec("rm", "-f", cached)
    if not exists(cached):
        exec("mkdir", "-p", CACHE_DIR)
        exec("wget", "--tries", "20", "--waitretry", "60",
             "--retry-connrefused", "-O", cached, url)
    return cached


def install_archive(url: str, dest: str, force: bool = False) -> str:
    """Download (or copy file://) an archive and extract it to dest
    (control/util.clj:149-233, simplified: tar + zip)."""
    with su():
        exec("rm", "-rf", dest) if force else None
        if not exists(dest):
            local = url[len("file://"):] if url.startswith("file://") else (
                cached_wget(url))
            tmp = tmp_dir()
            try:
                if local.endswith(".zip"):
                    exec("unzip", "-d", tmp, local)
                else:
                    exec("tar", "--no-same-owner", "--extract", "--file",
                         local, "--directory", tmp)
                entries = ls_full(tmp)
                src = entries[0] if len(entries) == 1 else tmp
                exec("mkdir", "-p", Lit(escape(dest).rsplit("/", 1)[0] or "/"))
                exec("mv", src, dest)
            finally:
                exec("rm", "-rf", tmp)
    return dest


def daemon_running(pidfile: str) -> Optional[bool]:
    """control/util.clj:243-257."""
    try:
        pid = exec("cat", pidfile)
    except RemoteError:
        return None
    try:
        exec("ps", "-p", pid)
        return True
    except RemoteError:
        return False


def start_daemon(opts: dict, bin: str, *args: Any) -> Any:
    """Start a daemon via start-stop-daemon (control/util.clj:259-287).

    opts: chdir, env (dict), logfile, make-pidfile? (default True),
    match-executable?, match-process-name?, pidfile, process-name."""
    pidfile = opts.get("pidfile")
    logfile = opts["logfile"]
    LOG.info("starting %s", bin.split("/")[-1])
    env = " ".join(
        f"{k}={escape(v)}" for k, v in (opts.get("env") or {}).items())
    cmd = ["start-stop-daemon", "--start", "--background",
           "--no-close", "--oknodo"]
    if opts.get("make-pidfile?", True) and pidfile:
        cmd += ["--make-pidfile"]
    if pidfile:
        cmd += ["--pidfile", pidfile]
    if opts.get("chdir"):
        cmd += ["--chdir", opts["chdir"]]
    if opts.get("match-executable?", True):
        cmd += ["--exec", bin]
    if opts.get("match-process-name?"):
        cmd += ["--name", opts.get("process-name", bin.split("/")[-1])]
    cmd += ["--startas", bin]
    cmd += ["--", *args]
    with su():
        full = (f"{env} " if env else "") + " ".join(
            escape(c) for c in cmd
        ) + f" >> {escape(logfile)} 2>&1"
        return exec_star(full)


def stop_daemon(pidfile: str, bin: Optional[str] = None) -> None:
    """Kill the daemon by pidfile (control/util.clj:289-315)."""
    LOG.info("stopping daemon %s", bin or pidfile)
    with su():
        if exists(pidfile):
            pid = exec("cat", pidfile)
            try:
                exec("kill", "-9", pid)
            except RemoteError:
                pass  # already gone
            exec("rm", "-rf", pidfile)


def grepkill(pattern: str, signal: Any = 9) -> None:
    """Kill processes matching a pattern (control/util.clj:235-241).

    ``ww`` is load-bearing: with a narrow COLUMNS exported in the
    executing environment, procps truncates each line EVEN WHEN PIPED,
    silently hiding matches past the cut — grepkill then no-ops while
    reporting success (caught by the ssh-subprocess integration tier)."""
    with su():
        try:
            exec_star(
                f"ps auxww | grep {escape(pattern)} | grep -v grep | "
                f"awk '{{print $2}}' | xargs -r kill -{signal}"
            )
        except RemoteError:
            pass


def signal(process_name: str, sig: Any) -> None:
    """Send a signal by process name (control/util.clj:317-321)."""
    with su():
        exec("pkill", "--signal", sig, process_name)
