"""Node network identity helpers (jepsen.control.net, control/net.clj):
resolve a hostname's IP from the node we're bound to, and our own IPs.
"""

from __future__ import annotations

from functools import lru_cache

from . import exec, exec_star


def ip_uncached(host: str) -> str:
    """Resolve host -> IP via getent on the bound node
    (control/net.clj:14-31)."""
    out = exec_star(
        f"getent ahosts {host} | head -n1 | cut -d' ' -f1"
    )
    return out.strip()


@lru_cache(maxsize=1024)
def ip(host: str) -> str:
    return ip_uncached(host)


def local_ip() -> str:
    """The bound node's first IP (control/net.clj:40-47)."""
    return exec_star("hostname -I | cut -d' ' -f1").strip()


def control_ip() -> str:
    """The control node's IP as seen locally (control/net.clj:49-57)."""
    import socket

    return socket.gethostbyname(socket.gethostname())
