"""Alternate remotes: `docker exec` and `kubectl exec`.

Mirrors jepsen/src/jepsen/control/docker.clj:75-90 and control/k8s.clj:
79-111 — drop-in Remote implementations so tests drive containerized
clusters without SSH.
"""

from __future__ import annotations

import subprocess
from typing import Any, Optional

from . import Remote, RemoteError


class DockerRemote(Remote):
    """Runs actions via ``docker exec`` and copies via ``docker cp``
    (control/docker.clj:75-90). The node name is the container name."""

    def __init__(self, container: Any = None):
        self.container = container

    def connect(self, host):
        return DockerRemote(host)

    def execute(self, action):
        p = subprocess.run(
            ["docker", "exec", "-i", str(self.container), "bash", "-c",
             action["cmd"]],
            input=(action.get("in") or "").encode() or None,
            capture_output=True,
        )
        return {"out": p.stdout.decode(errors="replace"),
                "err": p.stderr.decode(errors="replace"),
                "exit": p.returncode}

    def upload(self, local_paths, remote_path):
        paths = local_paths if isinstance(local_paths, (list, tuple)) else [
            local_paths]
        for lp in paths:
            p = subprocess.run(
                ["docker", "cp", str(lp), f"{self.container}:{remote_path}"],
                capture_output=True)
            if p.returncode:
                raise RemoteError({
                    "cmd": "docker cp", "host": self.container,
                    "exit": p.returncode,
                    "err": p.stderr.decode(errors="replace"), "out": ""})

    def download(self, remote_paths, local_path):
        paths = remote_paths if isinstance(remote_paths, (list, tuple)) else [
            remote_paths]
        for rp in paths:
            p = subprocess.run(
                ["docker", "cp", f"{self.container}:{rp}", str(local_path)],
                capture_output=True)
            if p.returncode:
                raise RemoteError({
                    "cmd": "docker cp", "host": self.container,
                    "exit": p.returncode,
                    "err": p.stderr.decode(errors="replace"), "out": ""})


class K8sRemote(Remote):
    """Runs actions via ``kubectl exec`` (control/k8s.clj:79-111). The
    node name is the pod name."""

    def __init__(self, pod: Any = None, namespace: Optional[str] = None,
                 container: Optional[str] = None):
        self.pod = pod
        self.namespace = namespace
        self.container = container

    def connect(self, host):
        return K8sRemote(host, self.namespace, self.container)

    def _base(self) -> list:
        cmd = ["kubectl"]
        if self.namespace:
            cmd += ["-n", self.namespace]
        return cmd

    def execute(self, action):
        cmd = self._base() + ["exec", "-i", str(self.pod)]
        if self.container:
            cmd += ["-c", self.container]
        cmd += ["--", "bash", "-c", action["cmd"]]
        p = subprocess.run(
            cmd, input=(action.get("in") or "").encode() or None,
            capture_output=True)
        return {"out": p.stdout.decode(errors="replace"),
                "err": p.stderr.decode(errors="replace"),
                "exit": p.returncode}

    def upload(self, local_paths, remote_path):
        paths = local_paths if isinstance(local_paths, (list, tuple)) else [
            local_paths]
        for lp in paths:
            p = subprocess.run(
                self._base() + ["cp", str(lp), f"{self.pod}:{remote_path}"],
                capture_output=True)
            if p.returncode:
                raise RemoteError({
                    "cmd": "kubectl cp", "host": self.pod,
                    "exit": p.returncode,
                    "err": p.stderr.decode(errors="replace"), "out": ""})

    def download(self, remote_paths, local_path):
        paths = remote_paths if isinstance(remote_paths, (list, tuple)) else [
            remote_paths]
        for rp in paths:
            p = subprocess.run(
                self._base() + ["cp", f"{self.pod}:{rp}", str(local_path)],
                capture_output=True)
            if p.returncode:
                raise RemoteError({
                    "cmd": "kubectl cp", "host": self.pod,
                    "exit": p.returncode,
                    "err": p.stderr.decode(errors="replace"), "out": ""})
