"""JAX platform/device pinning helpers.

One shared implementation of the "force a CPU platform with N virtual
devices" recipe used by both the test harness (tests/conftest.py) and the
driver entry (__graft_entry__.dryrun_multichip) — the multi-chip sharding
paths run on a virtual CPU mesh when TPU hardware isn't attached.

Must run before any JAX backend initializes. The image's sitecustomize
registers an `axon` TPU-relay PJRT backend in every process and pins
JAX_PLATFORMS=axon; when the relay is wedged the first jax op hangs
forever, so CPU-only work must drop the non-CPU factories in-process, not
just set env vars.
"""

from __future__ import annotations

import os
import re


def force_cpu_devices(n_devices: int = 8) -> None:
    """Pin this process to a CPU platform with ``n_devices`` virtual XLA
    devices, replacing any conflicting device-count flag. Safe to call
    repeatedly; rebuilds the backend if one already initialized with fewer
    devices."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
    want = f"--xla_force_host_platform_device_count={n_devices}"
    os.environ["XLA_FLAGS"] = (flags + " " + want).strip()

    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    _xb._backend_factories.pop("tpu", None)
    jax.config.update("jax_platforms", "cpu")
    if _xb._backends:
        try:
            n = len(jax.devices())
        except Exception:
            n = 0
        if n < n_devices:
            from jax.extend.backend import clear_backends

            clear_backends()
