"""Interactive exploration helpers (jepsen.repl, jepsen/src/jepsen/
repl.clj): load stored runs and poke at histories from a python shell.

    >>> from jepsen_tpu import repl
    >>> t = repl.latest()
    >>> h = t["history"]
    >>> repl.recheck(t)
"""

from __future__ import annotations

from typing import Any, Optional

from . import core, store


def latest(root: Optional[Any] = None) -> Optional[dict]:
    """The most recent stored test, with its history loaded."""
    return store.latest(root=root)


def load(name: str, start: str, root: Optional[Any] = None) -> dict:
    return store.load_test(name, start, root=root)


def recheck(test: dict, checker=None) -> dict:
    """Re-run analysis on a loaded test (optionally with a different
    checker) — the repl-sized version of the `analyze` command."""
    t = dict(test)
    t["no-store?"] = True
    if checker is not None:
        t["checker"] = checker
    return core.analyze(t)["results"]
