"""Interactive exploration helpers (jepsen.repl, jepsen/src/jepsen/
repl.clj): load stored runs and poke at histories from a python shell.

    >>> from jepsen_tpu import repl
    >>> t = repl.latest()
    >>> h = t["history"]
    >>> repl.recheck(t, checker.linearizable(model=CasRegister(init=0)))
"""

from __future__ import annotations

from typing import Any, Optional

from . import core, store


def latest(root: Optional[Any] = None) -> Optional[dict]:
    """The most recent stored test, with its history loaded."""
    return store.latest(root=root)


def load(name: str, start: str, root: Optional[Any] = None) -> dict:
    return store.load_test(name, start, root=root)


def recheck(test: dict, checker) -> dict:
    """Re-run analysis on a loaded test with the given checker — the
    repl-sized version of the `analyze` command. A checker must be
    supplied: live checkers are never persisted in the store
    (store.serializable_test strips them), so there is nothing to
    re-run without one."""
    if checker is None:
        raise ValueError(
            "recheck needs a checker: stored tests carry no live checker "
            "objects (e.g. pass checker=linearizable(model=...))")
    t = dict(test)
    t["no-store?"] = True
    t["checker"] = checker
    return core.analyze(t)["results"]
