"""Results browser: serve the ``store/`` tree over HTTP.

Mirrors jepsen.web (jepsen/src/jepsen/web.clj): a table of tests (name,
start time, validity) linking into each run's files, plain file serving
for history.edn / results.edn / jepsen.log / plots, and zip download of a
run (web.clj:48-69, served via cli serve — cli.clj:323-340); plus a
``/metrics`` page rendering each run's telemetry (metrics.jsonl, written
by runs with ``test["telemetry?"]``/``--telemetry``) next to the results
table, with the raw spans/metrics artifacts linked from the index.

Live operational view: ``/live`` serves an ndjson poll of every
registered *live source* — one JSON line per in-flight run, fed by the
online monitor's ``live_snapshot()`` (decided-watermark frontier,
per-key queue depths, scheduler backlog, decision-latency quantiles,
watermark-stall seconds, per-shard utilization). ``core.run`` registers
a source while a monitored run executes (and an in-process server when
``--live-port`` is set); ``/live.html`` is a self-refreshing dashboard
over the same feed. With no live run the endpoint still answers one
well-formed ``{"live_runs": 0}`` line, so pollers never special-case.
"""

from __future__ import annotations

import html
import io
import json
import logging
import threading
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any, Callable, Optional
from urllib.parse import unquote

from . import store

LOG = logging.getLogger("jepsen.web")


# ---------------------------------------------------------------------------
# Live sources: process-global so the serving handler (which only knows
# the store root) can reach in-flight runs registered by core.run.

_LIVE_LOCK = threading.Lock()
# key -> (registration ordinal, snapshot fn). The ordinal pins a STABLE
# registration-order listing: with many concurrent runs/services a
# poller must see the same row order every poll, and re-registering a
# key (a replaced source) must keep its original slot rather than
# jump to the end.
_LIVE_SOURCES: dict[str, tuple[int, Callable[[], dict]]] = {}
_LIVE_SEQ = 0


def register_live_source(key: str, fn: Callable[[], dict]) -> None:
    """Expose ``fn()`` (a dict snapshot, e.g. ``OnlineMonitor.
    live_snapshot`` or ``Service.live_snapshot``) as one ``/live`` line
    under ``key`` until unregistered. Re-registering a key replaces its
    source in place (the listing slot is the FIRST registration's)."""
    global _LIVE_SEQ
    with _LIVE_LOCK:
        prev = _LIVE_SOURCES.get(key)
        if prev is not None:
            _LIVE_SOURCES[key] = (prev[0], fn)
        else:
            _LIVE_SOURCES[key] = (_LIVE_SEQ, fn)
            _LIVE_SEQ += 1


def unregister_live_source(key: str) -> None:
    with _LIVE_LOCK:
        _LIVE_SOURCES.pop(key, None)


# Fleet sources: a router's ``fleet_snapshot()`` (backend states +
# scrape freshness + utilization, the router_state timeline, SLO burn
# rates) — the ``/fleet`` page renders every registered one. Same
# stable-ordinal registry semantics as the live sources.
_FLEET_SOURCES: dict[str, tuple[int, Callable[[], dict]]] = {}


def register_fleet_source(key: str, fn: Callable[[], dict]) -> None:
    """Expose ``fn()`` (a ``Router.fleet_snapshot``-shaped dict) on
    the ``/fleet`` page under ``key`` until unregistered."""
    global _LIVE_SEQ
    with _LIVE_LOCK:
        prev = _FLEET_SOURCES.get(key)
        if prev is not None:
            _FLEET_SOURCES[key] = (prev[0], fn)
        else:
            _FLEET_SOURCES[key] = (_LIVE_SEQ, fn)
            _LIVE_SEQ += 1


def unregister_fleet_source(key: str) -> None:
    with _LIVE_LOCK:
        _FLEET_SOURCES.pop(key, None)


def fleet_snapshots() -> list[dict]:
    """One snapshot per registered fleet source, registration order; a
    raising source yields an error row instead of sinking the page."""
    with _LIVE_LOCK:
        items = [(key, fn) for key, (order, fn)
                 in sorted(_FLEET_SOURCES.items(),
                           key=lambda kv: kv[1][0])]
    out = []
    for key, fn in items:
        try:
            snap = dict(fn())
        except Exception as e:  # noqa: BLE001 - a poll must not 500
            snap = {"error": f"{type(e).__name__}: {e}"}
        snap.setdefault("router", key)
        out.append(snap)
    return out


def live_snapshots() -> list[dict]:
    """One snapshot dict per registered source, in registration order;
    a source that raises yields an ``{"error": ...}`` line instead of
    sinking the poll."""
    with _LIVE_LOCK:
        items = [(key, fn) for key, (order, fn)
                 in sorted(_LIVE_SOURCES.items(),
                           key=lambda kv: kv[1][0])]
    out = []
    for key, fn in items:
        try:
            snap = dict(fn())
        except Exception as e:  # noqa: BLE001 - a poll must not 500
            snap = {"error": f"{type(e).__name__}: {e}"}
        if snap.get("run") is None:  # unnamed run: label with the key
            snap["run"] = key
        out.append(snap)
    return out


def live_ndjson() -> str:
    """The ``/live`` payload: one JSON line per live run, or one
    ``{"live_runs": 0}`` line when nothing is registered."""
    snaps = live_snapshots()
    if not snaps:
        return json.dumps({"live_runs": 0}) + "\n"
    return "".join(json.dumps(s, sort_keys=True, default=str) + "\n"
                   for s in snaps)


def _valid_of(run_dir: Path) -> Any:
    f = run_dir / "results.edn"
    if not f.exists():
        return None
    try:
        from . import edn

        m = edn.read_string(f.read_text())
        v = m.get(edn.K("valid?"))
        if isinstance(v, edn.Keyword):
            return v.name
        return v
    except Exception:
        return "?"


_STYLE = """
body { font-family: sans-serif; margin: 2em; }
table { border-collapse: collapse; }
td, th { border: 1px solid #ccc; padding: 0.3em 0.8em; text-align: left; }
.valid-true { background: #c8f7c5; } .valid-false { background: #f7c5c5; }
.valid-unknown { background: #f7eec5; }
"""


# Per-run artifacts the index row links to directly (the telemetry +
# tracing + profiling sinks; everything else is reachable through the
# file listing).
_TELEMETRY_FILES = ("metrics.jsonl", "metrics.prom", "spans.jsonl",
                    "profile.json", "flightrecord.json", "online.json",
                    "offline.json")

# Jepsen-parity plot/timeline artifacts (checker/perf.py writes the
# pngs, checker/timeline.py the html) — they existed in the store but
# nothing linked them; the index row surfaces them when present.
_PARITY_FILES = ("latency-raw.png", "latency-quantiles.png", "rate.png",
                 "timeline.html")


def _index_page(root: Path) -> str:
    rows = []
    tests = store.tests(root=root)
    for name in sorted(tests):
        for start in sorted(tests[name], reverse=True):
            run = tests[name][start]
            v = _valid_of(run)
            cls = {True: "valid-true", False: "valid-false",
                   "unknown": "valid-unknown"}.get(v, "")
            vs = {True: "valid", False: "INVALID",
                  "unknown": "unknown"}.get(v, "—")
            tele = " ".join(
                f'<a href="/files/{name}/{start}/{fn}">{fn}</a>'
                for fn in _TELEMETRY_FILES + _PARITY_FILES
                if (run / fn).exists()
            ) or "—"
            rows.append(
                f'<tr class="{cls}"><td><a href="/files/{name}/{start}/">'
                f'{html.escape(name)}</a></td>'
                f"<td>{html.escape(start)}</td><td>{vs}</td>"
                f"<td>{tele}</td>"
                f'<td><a href="/zip/{name}/{start}">zip</a></td></tr>'
            )
    return (
        f"<html><head><title>Jepsen</title><style>{_STYLE}</style></head>"
        "<body><h1>Jepsen tests</h1>"
        '<p><a href="/metrics">metrics</a> · '
        '<a href="/profile">profile</a> · '
        '<a href="/utilization">utilization</a> · '
        '<a href="/runs">runs</a> · '
        '<a href="/online">online</a> · '
        '<a href="/verdicts">verdicts</a> · '
        '<a href="/live.html">live</a> · '
        '<a href="/fleet">fleet</a></p><table>'
        "<tr><th>Test</th><th>Started</th><th>Valid?</th>"
        "<th>Telemetry</th><th></th></tr>"
        + "".join(rows) + "</table></body></html>"
    )


def _metrics_summary(run_dir: Path, limit: int = 200) -> list[tuple]:
    """Parse a run's metrics.jsonl into display rows
    (metric, labels, value) — histograms fold to count/mean, events to a
    per-name count."""
    f = run_dir / "metrics.jsonl"
    if not f.exists():
        return []
    rows: list[tuple] = []
    event_counts: dict[str, int] = {}
    try:
        with open(f) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                s = json.loads(line)
                kind = s.get("type")
                if kind == "event":
                    n = s.get("name", "?")
                    event_counts[n] = event_counts.get(n, 0) + 1
                    continue
                labels = ",".join(
                    f"{k}={v}" for k, v in sorted(
                        (s.get("labels") or {}).items()))
                if kind == "histogram":
                    cnt = s.get("count") or 0
                    mean = (s.get("sum") or 0) / cnt if cnt else 0
                    val = f"n={cnt} mean={mean:.4g}s"
                    # Buckets/quantiles, not just counts: the stored
                    # sample carries per-bucket counts — render the
                    # interpolated p50/p99 next to the mean (the
                    # decision-latency family is useless without them).
                    b = s.get("buckets") or {}
                    # metrics.jsonl is written sort_keys=True, which
                    # orders bucket keys LEXICALLY ("+Inf" first,
                    # "10.0" before "2.5") — re-sort numerically with
                    # +Inf last or bounds/counts feed bucket_quantile
                    # misaligned.
                    pairs = sorted(
                        ((float("inf") if k == "+Inf" else float(k), c)
                         for k, c in b.items()))
                    bounds = [k for k, _c in pairs if k != float("inf")]
                    if bounds and cnt:
                        from .telemetry.registry import bucket_quantile

                        counts = [c for _k, c in pairs]
                        p50 = bucket_quantile(bounds, counts, 0.5)
                        p99 = bucket_quantile(bounds, counts, 0.99)
                        val += (f" p50={p50:.4g}s p99={p99:.4g}s"
                                if p50 is not None and p99 is not None
                                else "")
                else:
                    v = s.get("value")
                    val = str(int(v)) if isinstance(v, (int, float)) \
                        and float(v).is_integer() else f"{v:.6g}"
                rows.append((s.get("name", "?"), labels, val))
    except Exception:
        return [("(unparseable metrics.jsonl)", "", "")]
    for n, c in sorted(event_counts.items()):
        rows.append((n, "(events)", str(c)))
    return rows[:limit]


def _metrics_page(root: Path) -> str:
    sections = []
    tests = store.tests(root=root)
    for name in sorted(tests):
        for start in sorted(tests[name], reverse=True):
            run = tests[name][start]
            rows = _metrics_summary(run)
            if not rows:
                continue
            body = "".join(
                f"<tr><td>{html.escape(m)}</td><td>{html.escape(l)}</td>"
                f"<td>{html.escape(v)}</td></tr>"
                for m, l, v in rows
            )
            links = " · ".join(
                f'<a href="/files/{name}/{start}/{fn}">{fn}</a>'
                for fn in _TELEMETRY_FILES if (run / fn).exists()
            )
            sections.append(
                f'<h2><a href="/files/{name}/{start}/">'
                f"{html.escape(name)} / {html.escape(start)}</a></h2>"
                f"<p>{links}</p><table>"
                "<tr><th>Metric</th><th>Labels</th><th>Value</th></tr>"
                + body + "</table>"
            )
    if not sections:
        sections.append(
            "<p>No runs with telemetry yet — run a test with "
            "<code>--telemetry</code>.</p>")
    return (
        f"<html><head><title>Jepsen metrics</title>"
        f"<style>{_STYLE}</style></head>"
        '<body><h1>Run metrics</h1><p><a href="/">index</a></p>'
        + "".join(sections) + "</body></html>"
    )


def _profile_rows(run_dir: Path) -> Optional[dict]:
    f = run_dir / "profile.json"
    if not f.exists():
        return None
    try:
        return json.loads(f.read_text())
    except Exception:
        return {"error": "unparseable profile.json"}


def _profile_section(doc: dict) -> str:
    """Render one run's profile.json: the device attribution's rung
    table + summary, batch occupancy, sharded interconnect share, and
    memory watermarks."""
    if doc.get("error"):
        return f"<p>{html.escape(doc['error'])}</p>"
    attr = doc.get("attribution") or {}
    parts = []
    dev = attr.get("device")
    if dev:
        s = dev.get("summary") or {}
        head = " · ".join(
            f"{k}: {v}" for k, v in sorted(s.items())
            if not isinstance(v, dict))
        bw = s.get("bound_wall_s") or {}
        if bw:
            head += " · wall by bound: " + ", ".join(
                f"{k}={v}s" for k, v in sorted(bw.items()))
        rows = "".join(
            "<tr>" + "".join(
                f"<td>{html.escape(str(r.get(k, '—')))}</td>"
                for k in ("F", "chunks", "levels", "wall_s",
                          "occupancy_mean", "achieved_gbs", "bound"))
            + "</tr>"
            for r in dev.get("rungs") or [])
        parts.append(
            f"<h3>Device search (roofline)</h3><p>{html.escape(head)}</p>"
            "<table><tr><th>F</th><th>chunks</th><th>levels</th>"
            "<th>wall s</th><th>occupancy</th><th>GB/s</th>"
            "<th>bound</th></tr>" + rows + "</table>")
    batch = attr.get("batch")
    if batch:
        rows = "".join(
            "<tr>" + "".join(
                f"<td>{html.escape(str(r.get(k, '—')))}</td>"
                for k in ("F", "members", "calls", "wall_s", "decided",
                          "overflowed", "occupancy_mean",
                          "occupancy_final"))
            + "</tr>"
            for r in batch.get("rungs") or [])
        parts.append(
            "<h3>Batched escalation (why members escalated)</h3>"
            "<table><tr><th>F</th><th>members</th><th>calls</th>"
            "<th>wall s</th><th>decided</th><th>overflowed</th>"
            "<th>occ mean</th><th>occ final</th></tr>"
            + rows + "</table>")
    sharded = attr.get("sharded")
    if sharded:
        ic = sharded.get("interconnect") or {}
        mode = sharded.get("exchange")
        head = f" ({html.escape(str(mode))} exchange)" if mode else ""
        parts.append(
            f"<h3>Frontier-sharded interconnect{head}</h3><p>"
            + html.escape(" · ".join(
                f"{k}: {v}" for k, v in sorted(ic.items())))
            + "</p>")
    marks = doc.get("memory_watermarks") or []
    if marks:
        rows = "".join(
            f"<tr><td>{html.escape(str(m.get('device')))}</td>"
            f"<td>{m.get('bytes_in_use', '—')}</td>"
            f"<td>{m.get('peak_bytes_in_use', '—')}</td></tr>"
            for m in marks)
        parts.append(
            "<h3>Device memory watermarks</h3>"
            "<table><tr><th>device</th><th>bytes in use</th>"
            "<th>peak bytes</th></tr>" + rows + "</table>")
    return "".join(parts) or "<p>(empty profile)</p>"


def _profile_page(root: Path) -> str:
    sections = []
    tests = store.tests(root=root)
    for name in sorted(tests):
        for start in sorted(tests[name], reverse=True):
            run = tests[name][start]
            doc = _profile_rows(run)
            if doc is None:
                continue
            links = " · ".join(
                f'<a href="/files/{name}/{start}/{fn}">{fn}</a>'
                for fn in ("profile.json", "flightrecord.json",
                           "metrics.jsonl") if (run / fn).exists())
            sections.append(
                f'<h2><a href="/files/{name}/{start}/">'
                f"{html.escape(name)} / {html.escape(start)}</a></h2>"
                f"<p>{links}</p>" + _profile_section(doc))
    if not sections:
        sections.append(
            "<p>No runs with profiles yet — run a test with "
            "<code>--profile</code>.</p>")
    return (
        f"<html><head><title>Jepsen profiles</title>"
        f"<style>{_STYLE}</style></head>"
        '<body><h1>Performance attribution</h1>'
        '<p><a href="/">index</a> · <a href="/metrics">metrics</a></p>'
        + "".join(sections) + "</body></html>"
    )


def _utilization_section(util: dict) -> str:
    """One run's utilization block (profile.json): the SVG occupancy
    Gantt (telemetry.utilization.render_gantt — gap-class colored) plus
    the per-device summary table."""
    from .telemetry import utilization as _util

    s = util.get("summary") or {}
    head = (
        f"<p>devices: {s.get('n_devices')} · mean utilization "
        f"{s.get('mean_utilization_pct')}% · makespan "
        f"{s.get('makespan_s')}s · critical path "
        f"{s.get('critical_path_pct')}%</p>")
    shares = s.get("gap_attribution_share") or {}
    if shares:
        head += ("<p>idle attribution: " + html.escape(", ".join(
            f"{k}={round(v * 100, 1)}%"
            for k, v in sorted(shares.items()))) + "</p>")
    rows = "".join(
        f"<tr><td>{d.get('device')}</td><td>{d.get('chunks')}</td>"
        f"<td>{d.get('busy_s')}</td><td>{d.get('utilization_pct')}</td>"
        f"<td>{html.escape(', '.join(f'{k}={v}' for k, v in sorted((d.get('gap_s') or {}).items())) or '—')}</td></tr>"
        for d in util.get("devices") or [])
    table = (
        "<table><tr><th>device</th><th>chunks</th><th>busy s</th>"
        "<th>util %</th><th>idle s by class</th></tr>" + rows
        + "</table>")
    try:
        gantt = _util.render_gantt(util)
    except Exception:  # noqa: BLE001 - a malformed block still lists
        gantt = ""
    return head + gantt + table


def _utilization_page(root: Path) -> str:
    sections = []
    tests = store.tests(root=root)
    for name in sorted(tests):
        for start in sorted(tests[name], reverse=True):
            run = tests[name][start]
            doc = _profile_rows(run)
            if doc is None:
                continue
            util = (doc.get("attribution") or {}).get("utilization")
            if not util:
                continue
            sections.append(
                f'<h2><a href="/files/{name}/{start}/">'
                f"{html.escape(name)} / {html.escape(start)}</a></h2>"
                + _utilization_section(util))
    if not sections:
        sections.append(
            "<p>No runs with utilization timelines yet — run a test "
            "with <code>--profile</code> (utilization is reconstructed "
            "from the timed chunk events and stored in "
            "profile.json).</p>")
    return (
        f"<html><head><title>Jepsen utilization</title>"
        f"<style>{_STYLE}</style></head>"
        "<body><h1>Device saturation</h1>"
        '<p><a href="/">index</a> · <a href="/profile">profile</a> · '
        '<a href="/runs">runs</a></p>'
        + "".join(sections) + "</body></html>"
    )


def _runs_page(root: Path) -> str:
    """The cross-run perf ledger's trend (store/ledger.jsonl), grouped
    by comparable (kind, workload, engine) with the newest-vs-previous
    deltas — regressions highlighted."""
    from .telemetry import ledger as _ledger

    # default_path honors the JEPSEN_LEDGER_PATH override, matching
    # every writer — a CI pointing writers elsewhere must see the same
    # file rendered here.
    records = _ledger.load(_ledger.default_path(root))
    sections = []
    for block in _ledger.trend(records):
        k = block["key"]
        cols = block["columns"]
        names = [n for n, _k, _d in _ledger.LEDGER_METRICS
                 if any(n in c["metrics"] for c in cols)]
        head_cells = "".join(f"<th>{html.escape(c['label'])}</th>"
                             for c in cols)
        body = ""
        regressed = set(block.get("regressions") or ())
        for n in names:
            cells = "".join(
                f"<td>{c['metrics'].get(n, '—')}</td>" for c in cols)
            cls = ' class="valid-false"' if n in regressed else ""
            body += f"<tr{cls}><td>{html.escape(n)}</td>{cells}</tr>"
        verd = "".join(f"<td>{html.escape(v)}</td>"
                       for v in block["verdicts"])
        body += f"<tr><td>verdict</td>{verd}</tr>"
        sections.append(
            f"<h2>{html.escape(k['kind'])} · {html.escape(k['workload'])}"
            f" <small>[engine={html.escape(k['engine'])}, "
            f"{block['records']} records]</small></h2>"
            f"<table><tr><th>metric</th>{head_cells}</tr>{body}</table>"
            + (("<p class=\"valid-false\">regressions vs previous: "
                + html.escape(", ".join(sorted(regressed))) + "</p>")
               if regressed else ""))
    if not sections:
        sections.append(
            "<p>No ledger yet — every run and bench leg appends one "
            "record to <code>store/ledger.jsonl</code>; gate with "
            "<code>python -m jepsen_tpu.ledger --check</code>.</p>")
    return (
        f"<html><head><title>Jepsen run ledger</title>"
        f"<style>{_STYLE}</style></head>"
        "<body><h1>Cross-run perf ledger</h1>"
        '<p><a href="/">index</a> · '
        '<a href="/utilization">utilization</a></p>'
        + "".join(sections) + "</body></html>"
    )


def _run_cause_counts(run_dir: Path) -> dict[str, dict[str, int]]:
    """Per-tenant ``{code: count}`` maps for one run, joined from the
    ``verdict_causes_total{code,tenant}`` samples in metrics.jsonl and
    the ``provenance`` block in online.json (tenant ``""`` = the run's
    own stream). Either source alone suffices — a run with only one of
    the two artifacts still renders."""
    out: dict[str, dict[str, int]] = {}
    f = run_dir / "metrics.jsonl"
    if f.exists():
        try:
            with open(f) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    s = json.loads(line)
                    if s.get("name") != "verdict_causes_total":
                        continue
                    labels = s.get("labels") or {}
                    code = labels.get("code")
                    if not code:  # the aggregate unlabeled total
                        continue
                    t = out.setdefault(labels.get("tenant") or "", {})
                    t[code] = t.get(code, 0) + int(s.get("value") or 0)
        except Exception:  # noqa: BLE001 - a bad artifact still lists
            pass
    f = run_dir / "online.json"
    if f.exists() and not out:
        try:
            doc = json.loads(f.read_text())
            causes = (doc.get("provenance") or {}).get("causes") or {}
            if causes:
                out[""] = {k: int(v) for k, v in causes.items()}
        except Exception:  # noqa: BLE001
            pass
    return out


def _verdicts_section(name: str, start: str,
                      tenants: dict[str, dict[str, int]]) -> str:
    """One run's cause Pareto: per-tenant tables with deep links into
    the trace chain (online.json segment table → spans.jsonl ids)."""
    from .checker import provenance as _prov

    parts = []
    links = " · ".join(
        [f'<a href="/files/{name}/{start}/online.json">online.json</a>',
         f'<a href="/files/{name}/{start}/metrics.jsonl">'
         "metrics.jsonl</a>",
         f'<a href="/files/{name}/{start}/spans.jsonl">spans.jsonl</a>',
         '<a href="/online">online</a>',
         '<a href="/utilization">utilization</a>',
         '<a href="/live.html">live</a>'])
    parts.append(f"<p>{links}</p>")
    for tenant in sorted(tenants):
        counts = tenants[tenant]
        label = (f"tenant <b>{html.escape(tenant)}</b>" if tenant
                 else "run stream")
        rows = "".join(
            f"<tr><td><code>{html.escape(r['code'])}</code></td>"
            f"<td>{html.escape(r['layer'])}</td>"
            f"<td>{r['count']}</td>"
            f"<td>{round(r['share'] * 100, 1)}%</td>"
            f"<td>{html.escape(r['description'])}</td></tr>"
            for r in _prov.pareto(counts))
        parts.append(
            f"<h3>{label} — {sum(counts.values())} attributed "
            "cause(s)</h3>"
            "<table><tr><th>cause</th><th>layer</th><th>count</th>"
            "<th>share</th><th>meaning</th></tr>" + rows + "</table>")
    return "".join(parts)


def _verdicts_page(root: Path) -> str:
    """The verdict-provenance browser: per-run / per-tenant cause
    Paretos (why did verdicts degrade to unknown), joined from the
    `verdict_causes_total` metric family and online.json provenance
    blocks, with the closed taxonomy reference at the bottom. See
    docs/verdicts.md."""
    from .checker import provenance as _prov

    sections = []
    tests = store.tests(root=root)
    for name in sorted(tests):
        for start in sorted(tests[name], reverse=True):
            run = tests[name][start]
            tenants = _run_cause_counts(run)
            if not tenants:
                continue
            sections.append(
                f'<h2><a href="/files/{name}/{start}/">'
                f"{html.escape(name)} / {html.escape(start)}</a></h2>"
                + _verdicts_section(name, start, tenants))
    if not sections:
        sections.append(
            "<p>No degraded verdicts recorded — every checked stream "
            "decided definitively (or no telemetry/online artifacts "
            "exist yet). Causes appear here the moment any verdict "
            "degrades to unknown.</p>")
    taxonomy = "".join(
        f"<tr><td><code>{html.escape(code)}</code></td>"
        f"<td>{html.escape(layer)}</td>"
        f"<td>{html.escape(desc)}</td></tr>"
        for code, (layer, desc) in sorted(_prov.TAXONOMY.items()))
    return (
        f"<html><head><title>Jepsen verdicts</title>"
        f"<style>{_STYLE}</style></head>"
        "<body><h1>Verdict provenance</h1>"
        '<p><a href="/">index</a> · <a href="/online">online</a> · '
        '<a href="/metrics">metrics</a> · '
        '<a href="/live.html">live</a> · advisor: '
        "<code>python -m jepsen_tpu.advisor</code></p>"
        + "".join(sections)
        + "<h2>Cause taxonomy (closed)</h2>"
        "<table><tr><th>code</th><th>layer</th><th>meaning</th></tr>"
        + taxonomy + "</table></body></html>"
    )


def _online_section(doc: dict) -> str:
    """Render one run's online.json: live watermark + verdict headline,
    detection info when a violation aborted the run, and the decided
    segment table."""
    v = doc.get("valid")
    vs = {True: "valid", False: "INVALID",
          "unknown": "unknown"}.get(v, str(v))
    cls = {True: "valid-true", False: "valid-false",
           "unknown": "valid-unknown"}.get(v, "")
    head = (
        f'<p class="{cls}">online verdict: <b>{html.escape(vs)}</b> · '
        f"decided through index {doc.get('decided_through_index')} of "
        f"{doc.get('ops_observed')} ops · "
        f"{doc.get('segments_decided')} segments"
        + (" · <b>run aborted on violation</b>" if doc.get("aborted")
           else "") + "</p>")
    if doc.get("ops_to_detection") is not None:
        head += (
            f"<p>detection: {doc['ops_to_detection']} ops / "
            f"{doc.get('seconds_to_detection')} s to the first invalid "
            "segment</p>")
    rows = "".join(
        "<tr>" + "".join(
            f"<td>{html.escape(str(s.get(k, '—')))}</td>"
            for k in ("seq", "key", "ops", "start_index", "end_index",
                      "valid", "engine", "members", "wall_s",
                      "terminal"))
        + "</tr>"
        for s in (doc.get("segments") or [])[:200])
    table = (
        "<table><tr><th>seq</th><th>key</th><th>ops</th><th>start</th>"
        "<th>end</th><th>valid</th><th>engine</th><th>members</th>"
        "<th>wall s</th><th>terminal</th></tr>" + rows + "</table>"
        if rows else "<p>(no segments decided)</p>")
    return head + table


def _offline_section(doc: dict) -> str:
    """Render an offline (segment-planner) result — the JSON
    ``python -m jepsen_tpu.offline -o .../offline.json`` writes (see
    docs/offline.md): verdict, plan shape, and per-stream decide
    attribution."""
    v = doc.get("valid")
    vs = {True: "valid", False: "INVALID",
          "unknown": "unknown"}.get(v, str(v))
    cls = {True: "valid-true", False: "valid-false",
           "unknown": "valid-unknown"}.get(v, "")
    plan = doc.get("plan") or {}
    util = (doc.get("utilization") or {}).get("mean_utilization_pct")
    busy = util if util is not None else doc.get("busy_pct")
    head = (
        f'<p class="{cls}">offline verdict: <b>{html.escape(vs)}</b> · '
        f"{doc.get('n_ops')} ops · engine {doc.get('engine')} · "
        f"{plan.get('n_items', '—')} items / "
        f"{plan.get('n_streams', '—')} streams · "
        f"plan {plan.get('plan_seconds', '—')} s · "
        f"wall {doc.get('wall_s', '—')} s"
        + (f" · busy {busy}%" if busy is not None else "") + "</p>")
    rows = "".join(
        "<tr>" + "".join(
            f"<td>{html.escape(str((row or {}).get(k, '—')))}</td>"
            for k in ("valid", "segments_decided", "decide_s"))
        + f"<td>{html.escape(name)}</td></tr>"
        for name, row in sorted((doc.get("streams") or {}).items()))
    table = (
        "<table><tr><th>valid</th><th>segments</th><th>decide s</th>"
        "<th>stream</th></tr>" + rows + "</table>" if rows else "")
    return head + table


def _online_page(root: Path) -> str:
    sections = []
    tests = store.tests(root=root)
    for name in sorted(tests):
        for start in sorted(tests[name], reverse=True):
            run = tests[name][start]
            f = run / "online.json"
            off = run / "offline.json"
            if not f.exists() and not off.exists():
                continue
            part = (
                f'<h2><a href="/files/{name}/{start}/">'
                f"{html.escape(name)} / {html.escape(start)}</a></h2>")
            if f.exists():
                try:
                    doc = json.loads(f.read_text())
                except Exception:
                    doc = None
                part += (
                    f'<p><a href="/files/{name}/{start}/online.json">'
                    "online.json</a></p>"
                    + (_online_section(doc) if doc is not None
                       else "<p>(unparseable online.json)</p>"))
            if off.exists():
                try:
                    odoc = json.loads(off.read_text())
                except Exception:
                    odoc = None
                part += (
                    f'<p><a href="/files/{name}/{start}/offline.json">'
                    "offline.json</a></p>"
                    + (_offline_section(odoc) if odoc is not None
                       else "<p>(unparseable offline.json)</p>"))
            sections.append(part)
    if not sections:
        sections.append(
            "<p>No runs with online monitoring yet — run a test with "
            "<code>--online</code>, or decide a recording with "
            "<code>python -m jepsen_tpu.offline ... -o "
            "store/&lt;test&gt;/&lt;start&gt;/offline.json</code>.</p>")
    return (
        f"<html><head><title>Jepsen online monitor</title>"
        f"<style>{_STYLE}</style></head>"
        "<body><h1>Online linearizability monitor</h1>"
        '<p><a href="/">index</a> · <a href="/metrics">metrics</a> · '
        '<a href="/profile">profile</a></p>'
        + "".join(sections) + "</body></html>"
    )


_LIVE_HTML = """<html><head><title>Jepsen live</title>
<style>%s
#none { color: #888; } .stall { background: #f7c5c5; }
pre { background: #f6f6f6; padding: 0.6em; }</style></head>
<body><h1>Live runs</h1>
<p><a href="/">index</a> · <a href="/metrics">metrics</a> ·
<a href="/online">online</a> · <a href="/verdicts">verdicts</a> ·
<a href="/fleet">fleet</a> · <a href="/alerts">alerts</a> ·
raw feed: <a href="/live">/live</a>
(ndjson poll)</p>
<div id="runs"><p id="none">polling /live…</p></div>
<script>
async function tick() {
  try {
    const txt = await (await fetch('/live')).text();
    const runs = txt.trim().split('\\n').map(JSON.parse);
    const box = document.getElementById('runs');
    if (runs.length === 1 && runs[0].live_runs === 0) {
      box.innerHTML = '<p id="none">no live runs — start one with ' +
                      '--online --live-port</p>';
    } else {
      box.innerHTML = runs.map(r => {
        const lat = r.decision_latency || {};
        const stall = (r.watermark_stall_seconds || 0) > 0;
        let head;
        let tenantTable = '';
        if (r.tenants) {
          // A multi-tenant service line: per-tenant depth/watermark
          // rows instead of the single-run monitor fields. A ROUTER
          // line (r.router) is service-shaped but spans backends, so
          // it adds a backend-state strip and may have no aggregate
          // latency histogram of its own.
          const p50 = lat.p50_s === undefined ? '-' : lat.p50_s;
          const p99 = lat.p99_s === undefined ? '-' : lat.p99_s;
          head = '<p>' + (r.draining ? 'DRAINING · ' : '') +
            (r.router ? 'ROUTER · ' : '') +
            (r.router && r.epoch !== undefined
              ? 'epoch ' + r.epoch + ' · ' : '') +
            r.tenant_count + ' tenants' +
            ' · ' + r.ops_observed + ' ops observed' +
            ' · backlog ' + r.scheduler_backlog +
            ' · p50/p99 decide ' + p50 + '/' + p99 + 's' +
            // Firing alerts ride the service's own /live line (the
            // alerting plane's rule names), red-badged inline.
            ((r.alerts && r.alerts.length)
              ? ' · alerts: ' + r.alerts.map(a =>
                  '<span class="stall">' + a + '</span>').join(' ')
              : '') +
            '</p>';
          if (r.backends) {
            head += '<p>backends: ' +
              Object.entries(r.backends).map(([n, b]) => {
                b = b || {};
                // respawn_gave_up is the terminal supervision state
                // (the flap circuit tripped); a respawn count next to
                // a live backend means the supervisor healed it.
                const bad = b.down || b.state === 'lost' ||
                  b.state === 'open' || b.respawn_gave_up;
                // Each backend row links to ITS OWN /live view; the
                // scrape cell mirrors the missing-latency guard — a
                // federated backend with no successful scrape renders
                // a typed "no scrape" marker, never a blank that
                // reads as healthy.
                const label = b.url
                  ? '<a href="' + b.url + '/live">' + n + '</a>' : n;
                let scrape = '';
                if (b.scrapes !== undefined) {
                  scrape = (b.scrape_age_s === undefined ||
                            b.scrape_age_s === null)
                    ? ' · <span class="stall">no scrape</span>'
                    : ' · scraped ' + b.scrape_age_s + 's ago' +
                      (b.scrape_stale
                        ? ' <span class="stall">STALE</span>' : '');
                }
                return (bad ? '<span class="stall">' : '') + label +
                  ' [' + (b.state || '?') + ']' +
                  (b.respawns ? ' ⟳' + b.respawns : '') + scrape +
                  (bad ? '</span>' : '');
              }).join(' · ') + '</p>';
          }
          tenantTable = '<table><tr><th>tenant</th><th>verdict</th>' +
            '<th>watermark</th><th>ops</th><th>queue</th>' +
            '<th>backlog</th><th>undecided</th><th>p99 s</th>' +
            '<th></th></tr>' +
            Object.entries(r.tenants).map(([name, t]) => {
              t = t || {};
              const tl = t.decision_latency || {};
              // Red row: a refuted stream OR a degraded one (lost
              // segments / unknown folds / journal append failures —
              // definite-True coverage is already compromised).
              const cls = (t.verdict === 'False' || t.degraded)
                ? ' class="stall"' : '';
              const flags = [
                t.aborted ? 'ABORTED' : '',
                // Why-unknown at a glance: the dominant taxonomy code
                // rides next to the DEGRADED flag (docs/verdicts.md).
                t.degraded ? ('DEGRADED' +
                  (t.dominant_unknown_cause
                    ? ' [' + t.dominant_unknown_cause + ']' : '')) : '',
                t.resumed_from_journal ? 'resumed' : '',
              ].filter(Boolean).join(' ');
              return '<tr' + cls + '><td>' + name + '</td>' +
                '<td>' + t.verdict + '</td>' +
                '<td>' + t.watermark + '</td>' +
                '<td>' + t.ops_observed + '</td>' +
                '<td>' + t.queue_depth + '</td>' +
                '<td>' + t.backlog + '</td>' +
                '<td>' + t.undecided_ops + '</td>' +
                '<td>' + tl.p99_s + '</td>' +
                '<td>' + flags + '</td></tr>';
            }).join('') + '</table>';
        } else {
          head = '<p' + (stall ? ' class="stall"' : '') + '>' +
            'verdict ' + r.verdict +
            ' · watermark ' + r.decided_through_index +
            ' / ' + r.ops_observed + ' ops' +
            ' · backlog ' + r.scheduler_backlog +
            ' · open ' + r.open_segment_ops + ' ops' +
            (stall ? ' · STALLED ' + r.watermark_stall_seconds + 's'
                   : '') +
            ' · p50/p99 decide ' + lat.p50_s + '/' + lat.p99_s + 's' +
            '</p>';
        }
        return '<h2>' + (r.run || '?') + '</h2>' + head + tenantTable +
          '<pre>' + JSON.stringify(r, null, 1) + '</pre>';
      }).join('');
    }
  } catch (e) { /* server gone: keep polling */ }
  setTimeout(tick, 1000);
}
tick();
</script></body></html>
"""


def _live_page() -> str:
    return _LIVE_HTML % _STYLE


# ---------------------------------------------------------------------------
# The fleet page: every registered router's fleet_snapshot — backend
# states + scrape freshness, the router_state.jsonl timeline, SLO burn
# rates, and a fleet Gantt (one lane per backend) over the scraped
# busy-span reconstructions.


def _merge_intervals(ivals: list) -> list[list[float]]:
    out: list[list[float]] = []
    for a, b in sorted((float(a), float(b)) for a, b in ivals):
        if out and a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return out


def _fleet_gantt(backends: dict) -> str:
    """One Gantt lane per backend from its scraped utilization block
    (chunk busy-spans when the backend ran device kernels, backlog
    occupancy otherwise), re-offset onto ONE shared wall-clock window
    so cross-backend idle gaps line up; unattributed idle renders as
    ``no-work`` gaps."""
    lanes = []  # (name, pct, abs window t0/t1, merged rel intervals)
    for name in sorted(backends):
        u = (backends[name] or {}).get("utilization") or {}
        w = u.get("window") or {}
        if not isinstance(w.get("t0"), (int, float)) \
                or not isinstance(w.get("t1"), (int, float)):
            continue
        if u.get("source") == "chunks":
            ivals = [iv for dev in (u.get("devices") or [])
                     for iv in (dev.get("intervals") or [])]
        else:
            ivals = list(u.get("intervals") or [])
        lanes.append((name, u.get("utilization_pct"),
                      float(w["t0"]), float(w["t1"]),
                      _merge_intervals(ivals)))
    if not lanes:
        return ""
    w0 = min(ln[2] for ln in lanes)
    w1 = max(ln[3] for ln in lanes)
    if w1 <= w0:
        return ""
    devices = []
    pcts = [ln[1] for ln in lanes if isinstance(ln[1], (int, float))]
    for name, pct, t0, t1, ivals in lanes:
        rel = [[round(a + t0 - w0, 6), round(b + t0 - w0, 6)]
               for a, b in ivals]
        gaps = []
        cursor = round(t0 - w0, 6)
        for a, b in rel + [[round(t1 - w0, 6), round(t1 - w0, 6)]]:
            if a - cursor > 1e-6:
                gaps.append({"t0_s": cursor, "t1_s": a,
                             "wall_s": round(a - cursor, 4),
                             "class": "no-work"})
            cursor = max(cursor, b)
        devices.append({"device": name, "utilization_pct": pct,
                        "intervals": rel, "gaps": gaps})
    util = {
        "window": {"t0": round(w0, 6), "t1": round(w1, 6),
                   "makespan_s": round(w1 - w0, 6)},
        "summary": {"mean_utilization_pct":
                    (round(sum(pcts) / len(pcts), 2)
                     if pcts else None)},
        "devices": devices,
    }
    from .telemetry import utilization as _util

    return _util.render_gantt(util)


def _fleet_section(snap: dict) -> str:
    name = html.escape(str(snap.get("router") or "?"))
    if snap.get("error"):
        return (f"<h2>{name}</h2><p class=\"stall\">"
                f"{html.escape(str(snap['error']))}</p>")
    bits = [f"epoch {snap.get('epoch')}"]
    if snap.get("draining"):
        bits.append("DRAINING")
    bits.append(f"{len(snap.get('backends') or {})} backends")
    orphans = snap.get("orphaned") or []
    if orphans:
        bits.append('<span class="stall">'
                    f"{len(orphans)} orphaned</span>")
    lat = snap.get("decision_latency") or {}
    if isinstance(lat.get("p99_s"), (int, float)):
        bits.append(f"fleet p50/p99 decide {lat.get('p50_s')}/"
                    f"{lat.get('p99_s')}s")
    parts = [f"<h2>{name}</h2><p>{' · '.join(bits)}</p>"]
    slo = snap.get("slo") or {}
    windows = slo.get("windows") or {}
    if windows:
        rows = "".join(
            f"<tr><td>{html.escape(k)}</td>"
            f"<td>{w.get('window_s')}</td>"
            f"<td>{w.get('availability_burn_rate')}</td>"
            f"<td>{w.get('latency_burn_rate')}</td>"
            f"<td>{w.get('decided')}</td>"
            f"<td>{w.get('rejected')}</td></tr>"
            for k, w in sorted(windows.items()))
        parts.append(
            "<h3>SLO burn rates</h3>"
            f"<p>availability target {slo.get('availability_target')}"
            f" · latency target {slo.get('latency_target_s')}s @ "
            f"p{slo.get('latency_ratio')}</p>"
            "<table><tr><th>window</th><th>s</th>"
            "<th>availability burn</th><th>latency burn</th>"
            "<th>decided</th><th>rejected</th></tr>"
            + rows + "</table>")
    alerts = snap.get("alerts") or {}
    firing = sorted(alerts.get("firing") or [])
    if firing:
        parts.append(
            "<h3>Alerts firing</h3><p>"
            + " ".join(f'<span class="stall">{html.escape(r)}</span>'
                       for r in firing)
            + ' · <a href="/alerts">details</a></p>')
    backends = snap.get("backends") or {}
    stale = set(snap.get("stale_backends") or [])
    brows = []
    for n in sorted(backends):
        b = backends[n] or {}
        bad = (b.get("down") or b.get("state") in ("lost", "open")
               or b.get("respawn_gave_up"))
        age = b.get("scrape_age_s")
        # The missing-scrape guard (the PR-14 missing-latency guard's
        # shape): a backend with no successful scrape renders a typed
        # placeholder, never a blank cell that reads as healthy.
        if age is None:
            scrape = '<span class="stall">no scrape</span>'
        else:
            scrape = f"{age}s ago"
            if b.get("scrape_stale") or n in stale:
                scrape += ' <span class="stall">STALE</span>'
        u = b.get("utilization") or {}
        pct = u.get("utilization_pct")
        util = "—" if pct is None else \
            f"{pct}% ({html.escape(str(u.get('source')))})"
        url = str(b.get("url") or "")
        link = (f'<a href="{html.escape(url)}/live">'
                f"{html.escape(n)}</a>" if url else html.escape(n))
        cls = ' class="stall"' if bad else ""
        brows.append(
            f"<tr{cls}><td>{link}</td>"
            f"<td>{html.escape(str(b.get('state') or '?'))}</td>"
            f"<td>{scrape}</td><td>{b.get('scrapes', 0)}</td>"
            f"<td>{util}</td>"
            f"<td>{len(b.get('tenants') or [])}</td></tr>")
    parts.append(
        "<h3>Backends</h3><table><tr><th>backend</th><th>state</th>"
        "<th>last scrape</th><th>scrapes</th><th>utilization</th>"
        "<th>tenants</th></tr>" + "".join(brows) + "</table>")
    gantt = _fleet_gantt(backends)
    if gantt:
        parts.append("<h3>Fleet timeline (busy spans)</h3>" + gantt)
    timeline = snap.get("timeline") or []
    if timeline:
        trows = []
        for rec in timeline[-40:]:
            detail = ", ".join(
                f"{k}={v}" for k, v in sorted(rec.items())
                if k not in ("kind", "t"))
            # Alert transitions ride the same timeline as placement /
            # respawn events so an operator can read them joined; a
            # firing row gets the stall tint.
            cls = ' class="stall"' if (rec.get("kind") == "alert"
                                       and rec.get("state") == "firing"
                                       ) else ""
            trows.append(
                f"<tr{cls}>"
                f"<td>{html.escape(str(rec.get('t', '—')))}</td>"
                f"<td>{html.escape(str(rec.get('kind')))}</td>"
                f"<td>{html.escape(detail)}</td></tr>")
        parts.append(
            "<h3>Router events &amp; alerts "
            "(router_state.jsonl + alerts.jsonl)</h3>"
            "<table><tr><th>t</th><th>kind</th><th>detail</th></tr>"
            + "".join(trows) + "</table>")
    return "".join(parts)


def _fleet_page() -> str:
    snaps = fleet_snapshots()
    if snaps:
        body = "".join(_fleet_section(s) for s in snaps)
    else:
        body = ("<p>No fleet sources — start a router with a metrics "
                "registry (<code>RouterConfig.federate</code>, the "
                "default) and <code>register_live=True</code>.</p>")
    return (
        "<html><head><title>Jepsen fleet</title>"
        '<meta http-equiv="refresh" content="2">'
        f"<style>{_STYLE}\n.stall {{ background: #f7c5c5; }}</style>"
        "</head><body><h1>Fleet</h1>"
        '<p><a href="/">index</a> · <a href="/live.html">live</a> · '
        '<a href="/metrics">metrics</a> · '
        'raw: <a href="/fleet.json">/fleet.json</a></p>'
        + body + "</body></html>")


def alert_snapshots() -> list[dict]:
    """One row per registered source that carries an alerting plane:
    routers contribute their fleet snapshot's ``alerts`` block (firing
    set + recent transitions), services contribute the firing-rule list
    their ``/live`` line carries. Sources without alerts are skipped —
    an empty store answers with an empty list, never an error."""
    out = []
    for snap in fleet_snapshots():
        al = snap.get("alerts")
        if isinstance(al, dict):
            out.append({
                "source": str(snap.get("router") or "?"),
                "kind": "router",
                "firing": sorted(al.get("firing") or []),
                "recent": list(al.get("recent") or []),
            })
    for line in live_snapshots():
        if line.get("router"):
            continue  # already covered via its fleet source
        al = line.get("alerts")
        if isinstance(al, list):
            out.append({
                "source": str(line.get("run") or "?"),
                "kind": "service",
                "firing": sorted(al),
                "recent": [],
            })
    return out


def _alerts_page() -> str:
    rows = alert_snapshots()
    parts = []
    for row in rows:
        name = html.escape(f"{row['source']} ({row['kind']})")
        firing = row["firing"]
        if firing:
            badge = " ".join(
                f'<span class="stall">{html.escape(r)}</span>'
                for r in firing)
            parts.append(f"<h2>{name}</h2><p>firing: {badge}</p>")
        else:
            parts.append(f"<h2>{name}</h2><p>no alerts firing</p>")
        recent = row["recent"]
        if recent:
            trows = []
            for rec in recent[-40:]:
                cls = ' class="stall"' \
                    if rec.get("state") == "firing" else ""
                trows.append(
                    f"<tr{cls}>"
                    f"<td>{html.escape(str(rec.get('t', '—')))}</td>"
                    f"<td>{html.escape(str(rec.get('rule')))}</td>"
                    f"<td>{html.escape(str(rec.get('state')))}</td>"
                    f"<td>{html.escape(str(rec.get('severity')))}</td>"
                    f"<td>{rec.get('generation')}</td></tr>")
            parts.append(
                "<table><tr><th>t</th><th>rule</th><th>state</th>"
                "<th>severity</th><th>gen</th></tr>"
                + "".join(trows) + "</table>")
    if not parts:
        parts.append(
            "<p>No alert sources — start a service or router with "
            "<code>--alerts</code> (or <code>alerts=True</code>) and "
            "<code>register_live=True</code>.</p>")
    return (
        "<html><head><title>Jepsen alerts</title>"
        '<meta http-equiv="refresh" content="2">'
        f"<style>{_STYLE}\n.stall {{ background: #f7c5c5; }}</style>"
        "</head><body><h1>Alerts</h1>"
        '<p><a href="/">index</a> · <a href="/fleet">fleet</a> · '
        '<a href="/live.html">live</a> · '
        'raw: <a href="/alerts.json">/alerts.json</a></p>'
        + "".join(parts) + "</body></html>")


def _listing_page(rel: str, d: Path) -> str:
    items = "".join(
        f'<li><a href="/files/{rel}{f.name}{"/" if f.is_dir() else ""}">'
        f"{html.escape(f.name)}</a></li>"
        for f in sorted(d.iterdir())
    )
    return (
        f"<html><head><style>{_STYLE}</style></head><body>"
        f"<h1>{html.escape(rel)}</h1><ul>{items}</ul></body></html>"
    )


def make_handler(root: Path):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            LOG.debug(fmt, *args)

        def _send(self, code: int, body: bytes,
                  ctype: str = "text/html; charset=utf-8"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = unquote(self.path)
            try:
                if path in ("/", "/index.html"):
                    self._send(200, _index_page(root).encode())
                    return
                if path in ("/metrics", "/metrics/"):
                    self._send(200, _metrics_page(root).encode())
                    return
                if path in ("/profile", "/profile/"):
                    self._send(200, _profile_page(root).encode())
                    return
                if path in ("/online", "/online/"):
                    self._send(200, _online_page(root).encode())
                    return
                if path in ("/verdicts", "/verdicts/"):
                    self._send(200, _verdicts_page(root).encode())
                    return
                if path in ("/utilization", "/utilization/"):
                    self._send(200, _utilization_page(root).encode())
                    return
                if path in ("/runs", "/runs/"):
                    self._send(200, _runs_page(root).encode())
                    return
                if path in ("/live", "/live/"):
                    self._send(200, live_ndjson().encode(),
                               "application/x-ndjson; charset=utf-8")
                    return
                if path == "/live.html":
                    self._send(200, _live_page().encode())
                    return
                if path in ("/fleet", "/fleet/"):
                    self._send(200, _fleet_page().encode())
                    return
                if path in ("/alerts", "/alerts/"):
                    self._send(200, _alerts_page().encode())
                    return
                if path == "/alerts.json":
                    self._send(
                        200,
                        json.dumps(alert_snapshots(), sort_keys=True,
                                   default=str).encode(),
                        "application/json")
                    return
                if path == "/fleet.json":
                    self._send(
                        200,
                        json.dumps(fleet_snapshots(), sort_keys=True,
                                   default=str).encode(),
                        "application/json")
                    return
                if path.startswith("/zip/"):
                    rel = path[len("/zip/"):].strip("/")
                    d = (root / rel).resolve()
                    if root.resolve() not in d.parents or not d.is_dir():
                        self._send(404, b"not found")
                        return
                    buf = io.BytesIO()
                    with zipfile.ZipFile(buf, "w") as z:
                        for f in d.rglob("*"):
                            if f.is_file():
                                z.write(f, f.relative_to(d.parent))
                    self._send(200, buf.getvalue(), "application/zip")
                    return
                if path.startswith("/files/"):
                    rel = path[len("/files/"):]
                    f = (root / rel.strip("/")).resolve()
                    if root.resolve() not in f.parents and f != root.resolve():
                        self._send(404, b"not found")
                        return
                    if f.is_dir():
                        self._send(
                            200,
                            _listing_page(
                                rel if rel.endswith("/") else rel + "/", f
                            ).encode(),
                        )
                        return
                    if f.is_file():
                        ctype = (
                            "text/html" if f.suffix == ".html"
                            else "image/png" if f.suffix == ".png"
                            else "image/svg+xml" if f.suffix == ".svg"
                            else "text/plain; charset=utf-8"
                        )
                        self._send(200, f.read_bytes(), ctype)
                        return
                self._send(404, b"not found")
            except Exception:
                LOG.warning("error serving %s", path, exc_info=True)
                self._send(500, b"internal error")

    return Handler


def server(root: Optional[Any] = None, port: int = 8080
           ) -> ThreadingHTTPServer:
    """Build (without starting) the HTTP server — tests drive this."""
    base = Path(root) if root else Path(store.BASE_DIR)
    return ThreadingHTTPServer(("", port), make_handler(base))


def serve(root: Optional[Any] = None, port: int = 8080) -> None:
    """Serve forever (cli.clj:323-340 seam)."""
    srv = server(root, port)
    LOG.info("Serving store on http://0.0.0.0:%d", port)
    print(f"Serving store on http://0.0.0.0:{port}")
    srv.serve_forever()
