"""Results browser: serve the ``store/`` tree over HTTP.

Mirrors jepsen.web (jepsen/src/jepsen/web.clj): a table of tests (name,
start time, validity) linking into each run's files, plain file serving
for history.edn / results.edn / jepsen.log / plots, and zip download of a
run (web.clj:48-69, served via cli serve — cli.clj:323-340).
"""

from __future__ import annotations

import html
import io
import json
import logging
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any, Optional
from urllib.parse import unquote

from . import store

LOG = logging.getLogger("jepsen.web")


def _valid_of(run_dir: Path) -> Any:
    f = run_dir / "results.edn"
    if not f.exists():
        return None
    try:
        from . import edn

        m = edn.read_string(f.read_text())
        v = m.get(edn.K("valid?"))
        if isinstance(v, edn.Keyword):
            return v.name
        return v
    except Exception:
        return "?"


_STYLE = """
body { font-family: sans-serif; margin: 2em; }
table { border-collapse: collapse; }
td, th { border: 1px solid #ccc; padding: 0.3em 0.8em; text-align: left; }
.valid-true { background: #c8f7c5; } .valid-false { background: #f7c5c5; }
.valid-unknown { background: #f7eec5; }
"""


def _index_page(root: Path) -> str:
    rows = []
    tests = store.tests(root=root)
    for name in sorted(tests):
        for start in sorted(tests[name], reverse=True):
            run = tests[name][start]
            v = _valid_of(run)
            cls = {True: "valid-true", False: "valid-false",
                   "unknown": "valid-unknown"}.get(v, "")
            vs = {True: "valid", False: "INVALID",
                  "unknown": "unknown"}.get(v, "—")
            rows.append(
                f'<tr class="{cls}"><td><a href="/files/{name}/{start}/">'
                f'{html.escape(name)}</a></td>'
                f"<td>{html.escape(start)}</td><td>{vs}</td>"
                f'<td><a href="/zip/{name}/{start}">zip</a></td></tr>'
            )
    return (
        f"<html><head><title>Jepsen</title><style>{_STYLE}</style></head>"
        "<body><h1>Jepsen tests</h1><table>"
        "<tr><th>Test</th><th>Started</th><th>Valid?</th><th></th></tr>"
        + "".join(rows) + "</table></body></html>"
    )


def _listing_page(rel: str, d: Path) -> str:
    items = "".join(
        f'<li><a href="/files/{rel}{f.name}{"/" if f.is_dir() else ""}">'
        f"{html.escape(f.name)}</a></li>"
        for f in sorted(d.iterdir())
    )
    return (
        f"<html><head><style>{_STYLE}</style></head><body>"
        f"<h1>{html.escape(rel)}</h1><ul>{items}</ul></body></html>"
    )


def make_handler(root: Path):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            LOG.debug(fmt, *args)

        def _send(self, code: int, body: bytes,
                  ctype: str = "text/html; charset=utf-8"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = unquote(self.path)
            try:
                if path in ("/", "/index.html"):
                    self._send(200, _index_page(root).encode())
                    return
                if path.startswith("/zip/"):
                    rel = path[len("/zip/"):].strip("/")
                    d = (root / rel).resolve()
                    if root.resolve() not in d.parents or not d.is_dir():
                        self._send(404, b"not found")
                        return
                    buf = io.BytesIO()
                    with zipfile.ZipFile(buf, "w") as z:
                        for f in d.rglob("*"):
                            if f.is_file():
                                z.write(f, f.relative_to(d.parent))
                    self._send(200, buf.getvalue(), "application/zip")
                    return
                if path.startswith("/files/"):
                    rel = path[len("/files/"):]
                    f = (root / rel.strip("/")).resolve()
                    if root.resolve() not in f.parents and f != root.resolve():
                        self._send(404, b"not found")
                        return
                    if f.is_dir():
                        self._send(
                            200,
                            _listing_page(
                                rel if rel.endswith("/") else rel + "/", f
                            ).encode(),
                        )
                        return
                    if f.is_file():
                        ctype = (
                            "text/html" if f.suffix == ".html"
                            else "image/png" if f.suffix == ".png"
                            else "image/svg+xml" if f.suffix == ".svg"
                            else "text/plain; charset=utf-8"
                        )
                        self._send(200, f.read_bytes(), ctype)
                        return
                self._send(404, b"not found")
            except Exception:
                LOG.warning("error serving %s", path, exc_info=True)
                self._send(500, b"internal error")

    return Handler


def server(root: Optional[Any] = None, port: int = 8080
           ) -> ThreadingHTTPServer:
    """Build (without starting) the HTTP server — tests drive this."""
    base = Path(root) if root else Path(store.BASE_DIR)
    return ThreadingHTTPServer(("", port), make_handler(base))


def serve(root: Optional[Any] = None, port: int = 8080) -> None:
    """Serve forever (cli.clj:323-340 seam)."""
    srv = server(root, port)
    LOG.info("Serving store on http://0.0.0.0:%d", port)
    print(f"Serving store on http://0.0.0.0:{port}")
    srv.serve_forever()
