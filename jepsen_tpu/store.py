"""Persistence: the ``store/`` directory tree.

Mirrors jepsen.store (jepsen/src/jepsen/store.clj). Each run writes
``store/<test-name>/<timestamp>/`` containing:

- ``history.edn``  — the full history, reference-compatible EDN
  (store.clj:345-362); archived reference histories load back through the
  same codec, so either side's histories replay on either checker.
- ``results.edn``  — the checker output (store.clj:231-241,385-397).
- ``test.edn``     — the serializable slice of the test map. (The reference
  stores the whole test as Fressian binary, store.clj:31-116; EDN is this
  build's single serialization format.)
- ``jepsen.log``   — per-run log file (store.clj:411-439).

plus ``latest`` / ``current`` symlinks (store.clj:296-333) and two-phase
saves: :func:`save_1` pre-analysis (history is durable even if the checker
dies), :func:`save_2` post-analysis (store.clj:372-397).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time as _time
from pathlib import Path
from typing import Any, Iterable, Optional

from . import edn
from .edn import K
from .history import History, Op

LOG = logging.getLogger("jepsen.store")

BASE_DIR = "store"

_TIME_FORMAT = "%Y%m%dT%H%M%S"  # store.clj:118-124 (basic-date-time)


def time_str(t: Optional[float] = None) -> str:
    """Millisecond-resolution timestamp — the reference's basic-date-time
    carries millis, and runs started within the same second must not
    collide in the store tree."""
    now = _time.time() if t is None else t
    base = _time.strftime(_TIME_FORMAT, _time.gmtime(now))
    millis = int((now % 1) * 1000)
    return f"{base}.{millis:03d}Z"


def base(test_or_root: Any = None) -> Path:
    if isinstance(test_or_root, (str, Path)):
        return Path(test_or_root)
    if isinstance(test_or_root, dict) and test_or_root.get("store-root"):
        return Path(test_or_root["store-root"])
    return Path(BASE_DIR)


def path(test: dict, *more: str) -> Path:
    """store/<name>/<start-time>/... (store.clj:126-143)."""
    name = test.get("name")
    assert name, "test must have a name to have a store path"
    start = test.get("start-time")
    assert start, "test must have a start-time to have a store path"
    return base(test).joinpath(name, start, *more)


def path_mk(test: dict, *more: str) -> Path:
    """path!, creating parents (store.clj:145-147)."""
    p = path(test, *more)
    (p if not more else p.parent).mkdir(parents=True, exist_ok=True)
    return p


# ---------------------------------------------------------------------------
# EDN conversion for results/test maps


def _str_keyword_vals(k: str, v: Any) -> Any:
    # :valid? values are keywords in the reference (true/false/:unknown).
    if k == "valid" and v == "unknown":
        return K("unknown")
    return v


def to_edn_value(x: Any) -> Any:
    """Convert a Python result/test structure to EDN-shaped values: string
    dict keys become keywords ("valid" → :valid?); sets/tuples/lists pass
    through; objects that aren't EDN-representable become their repr
    string."""
    if isinstance(x, dict):
        out = {}
        for k, v in x.items():
            if isinstance(k, str):
                kk = K("valid?") if k == "valid" else K(k)
            else:
                kk = to_edn_value(k)
            out[kk] = (
                _str_keyword_vals(k, to_edn_value(v)) if isinstance(k, str) else to_edn_value(v)
            )
        return out
    if isinstance(x, (list, tuple)):
        return [to_edn_value(v) for v in x]
    if isinstance(x, (set, frozenset)):
        return {to_edn_value(v) for v in x}
    if x is None or isinstance(x, (bool, int, float, str, edn.Keyword, edn.Symbol)):
        return x
    if isinstance(x, History):
        return [op.to_edn() for op in x]
    if isinstance(x, Op):
        return x.to_edn()
    return repr(x)


_TEST_SKIP_KEYS = frozenset(
    # Live objects that don't serialize: protocols, generators, functions.
    ("client", "nemesis", "generator", "checker", "db", "os", "net", "remote",
     "barrier", "store", "history", "results",
     "telemetry-registry", "trace-collector")
)


def serializable_test(test: dict) -> dict:
    """The plain-data slice of a test map (the reference's Fressian
    write-handlers similarly elide live objects, store.clj:31-116)."""
    return {
        k: v for k, v in test.items()
        if k not in _TEST_SKIP_KEYS and not callable(v)
    }


# ---------------------------------------------------------------------------
# Writers (store.clj:345-397)


def write_history(test: dict) -> None:
    """history.edn + history.txt (store.clj:345-362)."""
    h = test.get("history")
    if h is None:
        return
    if not isinstance(h, History):
        h = History([Op.from_dict(o) if isinstance(o, dict) else o for o in h],
                    reindex=False)
    path_mk(test)
    h.save(path(test, "history.edn"))
    with open(path(test, "history.txt"), "w") as f:
        for op in h:
            f.write(f"{op.process}\t{op.type}\t{op.f}\t{op.value!r}"
                    + (f"\t{op.error!r}" if op.error is not None else "")
                    + "\n")


def write_results(test: dict) -> None:
    """results.edn (store.clj:231-241)."""
    res = test.get("results")
    if res is None:
        return
    with open(path_mk(test, "results.edn"), "w") as f:
        f.write(edn.write_string(to_edn_value(res)))
        f.write("\n")


def write_test(test: dict) -> None:
    with open(path_mk(test, "test.edn"), "w") as f:
        f.write(edn.write_string(to_edn_value(serializable_test(test))))
        f.write("\n")


def update_symlinks(test: dict) -> None:
    """store/latest + store/<name>/latest → this run (store.clj:307-333)."""
    target = path(test)
    for link in (base(test) / "latest", base(test) / test["name"] / "latest"):
        try:
            if link.is_symlink() or link.exists():
                link.unlink()
            link.symlink_to(os.path.relpath(target, link.parent))
        except OSError:
            LOG.warning("could not update symlink %s", link, exc_info=True)


def save_1(test: dict) -> dict:
    """Phase 1: history + test, before analysis (store.clj:372-383)."""
    write_test(test)
    write_history(test)
    update_symlinks(test)
    return test

def save_2(test: dict) -> dict:
    """Phase 2: results, after analysis (store.clj:385-397)."""
    write_results(test)
    write_test(test)
    return test


# ---------------------------------------------------------------------------
# Readers (store.clj:181-305)


def load_history(name: str, start: str, root=None) -> History:
    return History.load(base(root).joinpath(name, start, "history.edn"))


def load_results(name: str, start: str, root=None) -> Any:
    with open(base(root).joinpath(name, start, "results.edn")) as f:
        return edn.read_string(f.read())


def load_test(name: str, start: str, root=None) -> dict:
    """Reconstruct the stored slice of a test map (+ history when present).
    Keyword keys are normalised back to strings."""
    d = base(root).joinpath(name, start)
    out: dict = {}
    tf = d / "test.edn"
    if tf.exists():
        m = edn.read_string(tf.read_text())
        for k, v in m.items():
            out[k.name if isinstance(k, edn.Keyword) else k] = v
    hf = d / "history.edn"
    if hf.exists():
        out["history"] = History.load(hf)
    out.setdefault("name", name)
    out.setdefault("start-time", start)
    return out


def tests(name: Optional[str] = None, root=None) -> dict:
    """Map of test name -> start-time -> path (store.clj:275-294)."""
    b = base(root)
    out: dict = {}
    if not b.exists():
        return out
    names = [name] if name else [
        p.name for p in b.iterdir() if p.is_dir() and not p.is_symlink()
    ]
    for n in names:
        d = b / n
        if not d.is_dir():
            continue
        runs = {
            r.name: r for r in sorted(d.iterdir())
            if r.is_dir() and not r.is_symlink()
        }
        if runs:
            out[n] = runs
    return out


def latest(root=None) -> Optional[dict]:
    """The most recently started test, loaded (store.clj:296-305)."""
    best = None
    for n, runs in tests(root=root).items():
        for start in runs:
            if best is None or start > best[1]:
                best = (n, start)
    if best is None:
        return None
    return load_test(*best, root=root)


def delete(name: Optional[str] = None, root=None) -> None:
    """Delete stored runs for a test name, or everything (store.clj:450-458)."""
    b = base(root)
    target = b / name if name else b
    if target.exists():
        shutil.rmtree(target)


# ---------------------------------------------------------------------------
# Per-run logging (store.clj:411-439)


_log_handlers: dict = {}


class _JsonFormatter(logging.Formatter):
    """Structured log lines (the reference's --logging-json / unilog JSON
    appender, store.clj:399-439, cli.clj:89-90)."""

    def format(self, record):
        out = {
            "ts": self.formatTime(record),
            "level": record.levelname,
            "logger": record.name,
            "thread": record.threadName,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out)


def start_logging(test: dict) -> None:
    """Attach a jepsen.log file handler for this run (JSON lines when the
    test sets logging-json, cli --logging-json)."""
    f = path_mk(test, "jepsen.log")
    h = logging.FileHandler(f)
    if test.get("logging-json") or test.get("logging_json"):
        h.setFormatter(_JsonFormatter())
    else:
        h.setFormatter(logging.Formatter(
            "%(asctime)s{%(threadName)s} %(levelname)s %(name)s - %(message)s"
        ))
    root = logging.getLogger()
    if root.level > logging.INFO or root.level == logging.NOTSET:
        root.setLevel(logging.INFO)
    root.addHandler(h)
    _log_handlers[id(test)] = h


def stop_logging(test: dict) -> None:
    h = _log_handlers.pop(id(test), None)
    if h is not None:
        logging.getLogger().removeHandler(h)
        h.close()
