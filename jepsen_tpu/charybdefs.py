"""CharybdeFS disk-fault injection driver.

Mirrors the reference's charybdefs wrapper (charybdefs/src/jepsen/
charybdefs.clj): builds ScyllaDB's CharybdeFS (a FUSE passthrough
filesystem with a thrift control port) from source on each node
(charybdefs.clj:7-38), mounts ``/faulty`` over ``/real``
(charybdefs.clj:60-65), and injects faults — EIO on every op, or
probabilistic 1% faults — via the thrift control interface
(charybdefs.clj:72-85). The thrift calls run node-side through a small
python snippet (the reference uses an in-process thrift client; the
node-side client keeps our control plane dependency-free).
"""

from __future__ import annotations

from . import control as c
from .control import util as cu

REPO = "https://github.com/scylladb/charybdefs.git"
DIR = "/opt/jepsen/charybdefs"
MOUNT = "/faulty"
BACKING = "/real"


def install() -> None:
    """Build charybdefs + thrift from source (charybdefs.clj:7-38),
    mount /faulty over /real (charybdefs.clj:40-65)."""
    from .os_ import debian

    with c.su():
        debian.install(["git", "cmake", "g++", "fuse", "libfuse-dev",
                        "thrift-compiler", "libthrift-dev",
                        "python3-thrift"])
        c.exec("mkdir", "-p", "/opt/jepsen")
        with c.cd("/opt/jepsen"):
            if not cu.exists(DIR):
                c.exec("git", "clone", REPO, DIR)
        with c.cd(DIR):
            c.exec_star("thrift -r --gen cpp server.thrift && "
                        "cmake CMakeLists.txt && make")
        c.exec("mkdir", "-p", MOUNT, BACKING)
        c.exec_star(
            f"mount | grep -q {c.escape(MOUNT)} || "
            f"{DIR}/charybdefs {MOUNT} -omodules=subdir,subdir={BACKING}")


_THRIFT_SNIPPET = """
import sys
sys.path.insert(0, "{dir}/gen-py")
from thrift.transport import TSocket, TTransport
from thrift.protocol import TBinaryProtocol
from server import server
sock = TSocket.TSocket("127.0.0.1", 9090)
transport = TTransport.TBufferedTransport(sock)
client = server.Client(TBinaryProtocol.TBinaryProtocol(transport))
transport.open()
client.{call}
transport.close()
"""


def _thrift(call: str) -> None:
    """Run one thrift control call on the bound node."""
    snippet = _THRIFT_SNIPPET.format(dir=DIR, call=call)
    c.exec_star(f"python3 - <<'JEPSEN_EOF'\n{snippet}\nJEPSEN_EOF")


def break_all() -> None:
    """EIO on every filesystem op (charybdefs.clj:72-75)."""
    _thrift('set_all_fault(False, 5, 0, 100000, "", False, 0, False)')


def break_one_percent() -> None:
    """Probabilistic faults on 1% of ops (charybdefs.clj:77-80)."""
    _thrift('set_all_fault(True, 5, 1000, 0, "", False, 0, False)')


def clear() -> None:
    """Heal the filesystem (charybdefs.clj:82-85)."""
    _thrift("clear_all_faults()")
